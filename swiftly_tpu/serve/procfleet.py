"""Process fleet: the serve tier across REAL process boundaries.

`serve.fleet.ServeFleet` drills failover with threads in one process —
every kill is an injected exception. This module is the same serving
contract with the simulation removed: each replica is a separate OS
process (a spawned worker hosting a `SubgridService` over its own
prepared forward), the parent is a front-door router, and the only
thing crossing the boundary is `serve.ipc`'s versioned length-prefixed
frames. What the thread fleet asserted, this tier must *survive*:

* **Heartbeats on the wire.** Each worker's main loop sends a
  ``HEARTBEAT`` frame every lease interval; the parent's reader thread
  beats that worker's `HealthLease`. A silent socket IS the missed
  beat — ``SIGKILL -9`` needs no cooperation from the victim to be
  detected, because detection was never cooperative.
* **The ledger above the transport.** Routing is the same rendezvous
  hash (`serve.fleet._rendezvous_score` — pure integer, stable across
  processes), gated by per-worker `resilience.CircuitBreaker`s; every
  submitted request sits in a parent-side ledger until a terminal
  result lands, so requests in flight on a killed worker are re-routed
  to survivors (``proc.failovers``) with zero loss, exactly the thread
  fleet's failover discipline.
* **Cross-process L2.** The recorded stream is shared through the
  spill directory: `utils.spill.SpillCache.export_manifest` forces
  every entry to its atomic on-disk form, and each worker wraps a
  read-only `SharedSpillReader` in the UNCHANGED
  `parallel.streamed.CachedColumnFeed` — the ``stream_version`` /
  mid-patch gates read liveness from the fleet's stream-state file, so
  a worker that maps a stale or mid-patch L2 refuses and recomputes,
  exactly like the in-process feed. Entry files are immutable and
  renamed into place, so a worker killed mid-read can never leave a
  torn row for a survivor to observe.
* **Supervision with capped backoff.** A supervisor thread reaps dead
  workers (``waitpid`` — no zombies), restarts them with
  `resilience.retry.backoff_delay`-capped delays (``proc.restarts``),
  and the restarted worker re-earns trust through the breaker's
  half-open path — its trips are NOT erased by the restart.
* **Startup hygiene.** Fleet start sweeps run directories abandoned by
  a crashed parent: stale unix-socket files are removed
  (``proc.stale_sockets_swept``) and orphaned worker processes —
  identified by pidfile + cmdline marker, never by pid alone — are
  reaped (``proc.orphans_reaped``), mirroring `SpillCache`'s
  orphaned-``.tmp`` sweep.

* **A distributed observability plane.** Observability must not stop
  at the process boundary: each worker ships cumulative
  ``TELEMETRY`` frames (its metrics counters + stage timers) every
  heartbeat, and the parent registers one ``worker-<rid>`` source per
  slot (plus a ``router`` source) with an `obs.tower.ControlTower` —
  dead generations fold into a per-slot retired ledger (the cache
  fabric's ``drop_view`` discipline) so fleet totals NEVER regress on
  failover, and `validate_fleet_telemetry_artifact` proves the
  cross-process sums. REQUEST frames carry trace context (router span
  id + pid); workers publish their own Chrome timelines atomically and
  the parent merges them onto one clock (`obs.report.merge_traces`)
  using per-worker offsets estimated from the HELLO exchange. Each
  worker also keeps a **black box**: its flight-recorder ring is
  continuously appended to a per-generation JSONL with an atomically
  published index, and on worker death the supervisor exhumes the dead
  worker's ring and folds its tail into the parent's post-mortem — a
  SIGKILL victim still tells its own side of the story.

``bench.py --procfleet`` is the headline drill: a real mid-burst
``SIGKILL -9``, zero lost requests, bit-identity to per-request
compute, the full lease→breaker→failover→half-open→closed cycle in the
artifact, and a second kill landed *while the victim holds an L2 read*
(the ``CONTROL`` dwell knob) to prove no torn row is observable
cross-process. See docs/serving.md "Process fleet" and
docs/observability.md "Distributed observability".
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

from ..obs import metrics as _metrics
from ..obs import recorder as _recorder
from ..obs import trace as _trace
from ..obs.tower import SLO
from ..resilience.breaker import CircuitBreaker
from ..resilience.faults import fault_point as _fault_point
from ..resilience.retry import backoff_delay, retry_transient
from . import ipc
from .fleet import _rendezvous_score
from .health import HealthLease, HealthMonitor
from .queue import (
    STATUS_EXPIRED,
    STATUS_SHED,
    RequestResult,
    SubgridRequest,
)

__all__ = [
    "ProcessFleet",
    "SharedSpillReader",
    "blackbox_index_path",
    "exhume_blackbox",
    "make_worker_spec",
]

log = logging.getLogger("swiftly-tpu.procfleet")

# cmdline marker the orphan sweep matches before it will signal a pid
# from a stale pidfile — a recycled pid can never be mistaken for ours.
WORKER_MARKER = "swiftly_tpu.serve.procfleet"

_LAT_RING = 4096
_STATE_FILE = "stream_state.json"
_SPEC_FILE = "spec.pkl"
_FLEET_PIDFILE = "fleet.pid"


def fleet_run_root():
    """Parent directory for every fleet's run dir (sockets, pidfiles,
    worker logs) — one fixed place so startup hygiene can find the
    wreckage of a crashed previous run."""
    return os.path.join(tempfile.gettempdir(), "swiftly_procfleet")


def make_worker_spec(params, sources, *, backend="planar", dtype="float32",
                     max_depth=256, max_batch=16, max_retries=2,
                     lru_forward=2, queue_size=64, lease_interval_s=0.02,
                     stream=None):
    """The picklable recipe a worker process rebuilds its serving stack
    from: catalogue ``params`` + point ``sources`` (the facet data is
    deterministic given both), service knobs, and optionally the
    recorded stream's manifest (`SpillCache.export_manifest`) for
    cross-process L2 serving."""
    return {
        "params": dict(params),
        "sources": list(sources),
        "backend": backend,
        "dtype": str(dtype),
        "max_depth": int(max_depth),
        "max_batch": int(max_batch),
        "max_retries": int(max_retries),
        "lru_forward": int(lru_forward),
        "queue_size": int(queue_size),
        "lease_interval_s": float(lease_interval_s),
        "stream": stream,
    }


# ---------------------------------------------------------------------------
# Cross-process L2: read-only view over an exported spill manifest
# ---------------------------------------------------------------------------


class SharedSpillReader:
    """Duck-typed `utils.spill.SpillCache` read surface over an
    exported manifest, for a feed in ANOTHER process.

    `parallel.streamed.CachedColumnFeed` gates every lookup on the
    backing cache's ``complete`` / ``patching`` / ``stream_version``
    attributes; here those are properties that re-read the owning
    fleet's stream-state file, so the in-process gate semantics carry
    across the boundary unchanged: the parent flips the state file and
    every worker's feed starts refusing (LookupError → the service's
    fall-back-to-compute path) without any extra protocol.

    ``dwell_s`` is the drill knob behind the ``CONTROL`` frame: a
    positive value makes the next `get_row` hold its memory-mapped
    read open for that long (announcing itself through
    ``dwell_flag_path``), giving ``bench.py --procfleet`` a real
    mid-L2-read window to land a ``SIGKILL`` in.
    """

    def __init__(self, manifest, state_path, dwell_flag_path=None):
        self._entries = list(manifest["entries"])
        self._meta = list(manifest["meta"])
        self._state_path = state_path
        self._export_version = int(manifest.get("stream_version", 0))
        self.dwell_s = 0.0
        self.dwell_flag_path = dwell_flag_path
        self.flush_hook = None  # black-box sync point before the flag
        self.rows_read = 0

    def _state(self):
        try:
            with open(self._state_path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            # no state file, or a torn/partial write: refuse — the feed
            # sees an incomplete cache and the service recomputes
            return {"complete": False, "patching": True,
                    "stream_version": -1}

    @property
    def complete(self):
        return bool(self._state().get("complete", False))

    @property
    def patching(self):
        return bool(self._state().get("patching", True))

    @property
    def stream_version(self):
        return int(self._state().get("stream_version", -1))

    def __len__(self):
        return len(self._meta)

    def meta(self, k):
        return self._meta[k]

    def get_row(self, k, index):
        def read():
            _fault_point("spill.get_row")
            mm = np.load(self._entries[k], mmap_mode="r")
            if self.dwell_s > 0:
                # hold the mapped read open: the drill's kill window
                _recorder.record("proc", "proc.l2_dwell",
                                 f"entry={k} dwell_s={self.dwell_s}")
                if self.flush_hook is not None:
                    # persist the dwell event BEFORE announcing the
                    # window — the SIGKILL that the flag invites lands
                    # faster than the next heartbeat-cadence flush, and
                    # the exhumed black box must show the dwell
                    self.flush_hook()
                if self.dwell_flag_path:
                    with open(self.dwell_flag_path, "w") as fh:
                        fh.write(str(os.getpid()))
                time.sleep(self.dwell_s)
            row = np.array(mm[index])
            _metrics.count("proc.l2_rows_read")
            return row

        out = retry_transient(read, site="spill.get_row")
        self.rows_read += 1
        return out


def write_stream_state(path, *, stream_version, complete=True,
                       patching=False):
    """Atomically publish the stream's liveness for cross-process
    readers (tmp sibling + rename — a reader can never see a torn
    state file, only the old one or the new one)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"stream_version": int(stream_version),
                   "complete": bool(complete),
                   "patching": bool(patching)}, fh)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# Black-box recorder: a worker's flight-recorder ring, persisted
# continuously so a SIGKILL victim still tells its own story
# ---------------------------------------------------------------------------


def blackbox_index_path(run_dir, rid):
    """The atomically published black-box index for one worker slot."""
    return os.path.join(run_dir, f"blackbox-{rid}.idx.json")


def _blackbox_events_file(rid, generation):
    return f"blackbox-{rid}.g{generation}.jsonl"


class _WorkerBlackBox:
    """Worker-side black-box flusher: continuously persists the
    flight-recorder ring so the story survives ``SIGKILL -9``.

    Two-file discipline, mirroring `write_stream_state`:

    * the per-generation events file (``blackbox-<rid>.g<G>.jsonl``)
      is append-only — each flush drains
      `obs.recorder.FlightRecorder.events_since` and appends one JSON
      line per event. A kill mid-write leaves at most one torn trailing
      line, which `exhume_blackbox` skips;
    * the index (``blackbox-<rid>.idx.json``) is published atomically
      (tmp sibling + rename) naming the current generation, events file
      and count — an exhumer can never read a torn index, only the
      previously published one.
    """

    def __init__(self, run_dir, rid, generation, recorder):
        self.run_dir = run_dir
        self.rid = int(rid)
        self.generation = int(generation)
        self.recorder = recorder
        self.events_file = _blackbox_events_file(rid, generation)
        self.n_events = 0
        self._watermark = -1.0
        self._published = -1
        self._lock = threading.Lock()  # heartbeat loop vs dwell hook
        self._fh = open(os.path.join(run_dir, self.events_file), "a")

    def flush(self):
        """Append everything the ring recorded since the last flush,
        then republish the index if the count moved."""
        with self._lock:
            evs, self._watermark = self.recorder.events_since(
                self._watermark)
            if evs:
                for e in evs:
                    self._fh.write(json.dumps(e) + "\n")
                self._fh.flush()
                self.n_events += len(evs)
            if self.n_events != self._published:
                self._publish_index()
            return len(evs)

    def _publish_index(self):
        path = blackbox_index_path(self.run_dir, self.rid)
        tmp = f"{path}.tmp{self.generation}"
        with open(tmp, "w") as fh:
            json.dump({"rid": self.rid, "generation": self.generation,
                       "events_file": self.events_file,
                       "n_events": self.n_events,
                       "t_epoch": time.time()}, fh)
        os.replace(tmp, path)
        self._published = self.n_events

    def close(self):
        try:
            self.flush()
        except Exception:
            pass
        try:
            self._fh.close()
        except Exception:
            pass


def _read_jsonl_tolerant(path):
    """Events from one black-box JSONL, or None if unreadable. A torn
    trailing line — the write the kill interrupted — ends the replay
    instead of raising: everything before it is intact by append-order."""
    try:
        with open(path) as fh:
            raw = fh.read()
    except OSError:
        return None
    events = []
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            e = json.loads(line)
        except ValueError:
            break  # torn tail: stop at the interrupted write
        if isinstance(e, dict):
            events.append(e)
    return events


def exhume_blackbox(run_dir, rid, max_generation=None):
    """Exhume a dead worker's black box: read the atomically published
    index, then replay the events file it names.

    A torn or missing index falls back to scanning per-generation
    events files downward from ``max_generation`` — the last
    generation that managed to persist anything still tells its story.
    Returns ``{rid, generation, n_events, events, t_epoch,
    torn_index}`` or None when the worker left nothing readable."""
    idx = None
    torn_index = False
    try:
        with open(blackbox_index_path(run_dir, rid)) as fh:
            idx = json.load(fh)
    except ValueError:
        torn_index = True
    except OSError:
        pass
    from_index = isinstance(idx, dict) and idx.get("events_file")
    if from_index:
        candidates = [(int(idx.get("generation", 0)),
                       os.path.join(run_dir, idx["events_file"]))]
    else:
        top = int(max_generation) if max_generation else 8
        candidates = [
            (g, os.path.join(run_dir, _blackbox_events_file(rid, g)))
            for g in range(top, 0, -1)
        ]
    for generation, path in candidates:
        events = _read_jsonl_tolerant(path)
        if events is None:
            continue
        if not events and not from_index:
            continue  # empty fallback candidate: try the older one
        return {
            "rid": int(rid),
            "generation": int(generation),
            "n_events": len(events),
            "events": events,
            "t_epoch": (idx or {}).get("t_epoch")
            if isinstance(idx, dict) else None,
            "torn_index": torn_index,
        }
    return None


# ---------------------------------------------------------------------------
# Worker process
# ---------------------------------------------------------------------------


def _worker_serving_stack(spec, run_dir, rid):
    """Rebuild config → facets → forward → service from the spec.
    Imports live here: the parent pays them once, each worker pays
    them at boot (the supervisor's lease registration waits for the
    first heartbeat, so boot time never reads as a missed beat)."""
    import jax

    from .. import (
        SwiftlyConfig,
        SwiftlyForward,
        make_facet,
        make_full_facet_cover,
    )
    from ..parallel.streamed import CachedColumnFeed
    from .queue import AdmissionQueue
    from .scheduler import CoalescingScheduler
    from .service import SubgridService

    dtype = getattr(jax.numpy, spec["dtype"])
    config = SwiftlyConfig(
        backend=spec["backend"], dtype=dtype, **spec["params"])
    facet_configs = make_full_facet_cover(config)
    facet_tasks = [
        (fc, make_facet(config.image_size, fc, spec["sources"]))
        for fc in facet_configs
    ]
    fwd = SwiftlyForward(
        config, facet_tasks,
        lru_forward=spec["lru_forward"], queue_size=spec["queue_size"],
    )
    reader = None
    feed = None
    if spec.get("stream"):
        reader = SharedSpillReader(
            spec["stream"],
            os.path.join(run_dir, _STATE_FILE),
            dwell_flag_path=os.path.join(run_dir, f"l2_dwell_{rid}.flag"),
        )
        try:
            feed = CachedColumnFeed(
                reader, stream_version=reader.stream_version)
        except ValueError:
            feed = None  # stream not complete: serve pure compute
    service = SubgridService(
        fwd,
        queue=AdmissionQueue(max_depth=spec["max_depth"]),
        scheduler=CoalescingScheduler(max_batch=spec["max_batch"]),
        max_retries=spec["max_retries"],
        cache_feed=feed,
    )
    return service, reader


def _result_payload(req_id, res):
    data = res.data
    if data is not None:
        data = np.asarray(data)
    return {
        "req_id": req_id,
        "status": res.status,
        "data": data,
        "error": res.error,
        "latency_s": float(res.latency_s),
        "path": res.path,
        "retries": int(res.retries),
        "shed_reason": res.shed_reason,
        "retry_after_s": res.retry_after_s,
    }


def _worker_main(run_dir, rid, sock_path, generation=1):
    """Worker process entry: serve REQUEST frames over one unix socket,
    heartbeat every lease interval, drain on DRAIN. Runs until the
    parent drains it, the parent's socket dies, or it is killed.

    Observability boots with the worker: metrics + flight recorder are
    always on (telemetry frames and the black box need them), the
    tracer when the spec asks (``spec["trace"]``)."""
    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format=f"%(asctime)s worker-{rid}: %(message)s",
        stream=sys.stderr,
    )
    with open(os.path.join(run_dir, f"worker-{rid}.pid"), "w") as fh:
        fh.write(str(os.getpid()))
    with open(os.path.join(run_dir, _SPEC_FILE), "rb") as fh:
        spec = pickle.load(fh)

    _metrics.enable()
    _recorder.enable()
    tracing = bool(spec.get("trace"))
    if tracing:
        _trace.enable()
    trace_path = os.path.join(run_dir, f"trace-{rid}.g{generation}.json")
    blackbox = _WorkerBlackBox(run_dir, rid, generation,
                               _recorder.get_recorder())

    service, reader = _worker_serving_stack(spec, run_dir, rid)
    if reader is not None:
        reader.flush_hook = blackbox.flush

    lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except OSError:
        pass
    lsock.bind(sock_path)
    lsock.listen(1)
    lsock.settimeout(60.0)
    conn, _ = lsock.accept()

    service.start()
    stream = ipc.FrameStream(conn)
    hb_interval = float(spec["lease_interval_s"])
    pending = {}  # parent req_id -> SubgridRequest
    pending_trace = {}  # parent req_id -> (trace ctx, t_accept)
    served = 0
    beats = 0
    last_hb = 0.0
    last_trace_pub = 0.0
    running = True
    frame_deadline = max(1.0, 4 * hb_interval)

    def telemetry_snapshot():
        snap = _metrics.export()
        return {
            "rid": rid, "pid": os.getpid(), "generation": generation,
            "t_epoch": time.time(), "beats": beats, "served": served,
            "pending": len(pending),
            "counters": dict(snap.get("counters") or {}),
            "stages": {
                name: {"count": st.get("count", 0),
                       "total_s": st.get("total_s", 0.0)}
                for name, st in (snap.get("stages") or {}).items()
                if isinstance(st, dict)
            },
        }

    try:
        while running:
            now = time.monotonic()
            if now - last_hb >= hb_interval:
                beats += 1
                ipc.send_frame(
                    conn, ipc.FRAME_HEARTBEAT,
                    {"rid": rid, "beats": beats, "served": served,
                     "pending": len(pending)},
                    deadline_s=frame_deadline)
                last_hb = now
                # the observability plane rides the heartbeat cadence:
                # persist the ring, ship the cumulative snapshot
                blackbox.flush()
                ipc.send_frame(conn, ipc.FRAME_TELEMETRY,
                               telemetry_snapshot(),
                               deadline_s=frame_deadline)
                if tracing and now - last_trace_pub >= 0.5:
                    _trace.save(trace_path, atomic=True)
                    last_trace_pub = now
            for req_id in list(pending):
                freq = pending[req_id]
                if freq.done:
                    del pending[req_id]
                    ipc.send_frame(
                        conn, ipc.FRAME_RESULT,
                        _result_payload(req_id, freq.result),
                        deadline_s=frame_deadline)
                    served += 1
                    ctx, t_req = pending_trace.pop(req_id, (None, None))
                    if ctx and tracing:
                        # the worker half of the cross-process hop:
                        # xparent/xpid let merge_traces re-parent this
                        # span under the router's proc.request
                        _trace.add_span(
                            "proc.worker_request", t_req,
                            time.perf_counter(), cat="proc",
                            req_id=req_id, rid=rid,
                            status=freq.result.status,
                            xparent=ctx.get("span"),
                            xpid=ctx.get("pid"))
            try:
                ftype, _flags, obj = stream.recv_frame(
                    deadline_s=min(0.005, hb_interval / 4))
            except ipc.WireDeadline:
                continue
            except (ipc.TruncatedFrame, OSError):
                break  # parent gone: nothing left to serve
            except ipc.WireError as exc:
                # desynced stream cannot resync under length-prefixed
                # framing: report once, then drop the connection
                try:
                    ipc.send_frame(conn, ipc.FRAME_ERROR,
                                   {"rid": rid, "error": repr(exc)},
                                   deadline_s=frame_deadline)
                except ipc.WireError:
                    pass
                break
            if ftype == ipc.FRAME_REQUEST:
                _recorder.record("proc", "proc.request",
                                 f"req_id={obj['req_id']}")
                freq = service.submit(
                    obj["config"], priority=obj.get("priority", 0),
                    deadline_s=obj.get("deadline_s"))
                pending[obj["req_id"]] = freq
                pending_trace[obj["req_id"]] = (
                    obj.get("trace"), time.perf_counter())
            elif ftype == ipc.FRAME_HELLO:
                ipc.send_frame(
                    conn, ipc.FRAME_HELLO,
                    {"rid": rid, "pid": os.getpid(),
                     "wire_version": ipc.WIRE_VERSION,
                     "generation": generation,
                     # the wall-clock stamp the parent's NTP-style
                     # offset estimate anchors on (±rtt/2 uncertainty)
                     "t_epoch": time.time()},
                    deadline_s=frame_deadline)
            elif ftype == ipc.FRAME_CONTROL:
                if reader is not None and "dwell_l2_s" in obj:
                    reader.dwell_s = float(obj["dwell_l2_s"])
                ipc.send_frame(conn, ipc.FRAME_CONTROL, {"ack": True},
                               deadline_s=frame_deadline)
            elif ftype == ipc.FRAME_DRAIN:
                service.stop(drain=True)
                for req_id, freq in list(pending.items()):
                    res = freq.wait(timeout=5.0)
                    if res is not None:
                        ipc.send_frame(conn, ipc.FRAME_RESULT,
                                       _result_payload(req_id, res),
                                       deadline_s=frame_deadline)
                        served += 1
                pending.clear()
                ipc.send_frame(conn, ipc.FRAME_DRAIN,
                               {"rid": rid, "served": served},
                               deadline_s=frame_deadline)
                running = False
    finally:
        try:
            service.stop(drain=False)
        except Exception:
            pass
        blackbox.close()
        if tracing:
            try:
                _trace.save(trace_path, atomic=True)
            except Exception:
                pass
        for path in (sock_path, os.path.join(run_dir, f"worker-{rid}.pid")):
            try:
                os.unlink(path)
            except OSError:
                pass
        conn.close()
        lsock.close()
    return 0


# ---------------------------------------------------------------------------
# Parent side
# ---------------------------------------------------------------------------


class _Worker:
    """Parent-side handle for one worker process (one generation)."""

    def __init__(self, rid):
        self.rid = rid
        self.generation = 0
        self.proc = None
        self.sock = None    # reader-thread side
        self.wsock = None   # sender side: a dup()'d object so send and
        #                     recv timeouts never race on one socket
        self.sock_path = None
        self.send_lock = threading.Lock()
        self.reader_thread = None
        self.lease = None
        self.breaker = None
        self.ready = False      # hello + first heartbeat seen
        self.dead = True
        self.restarts = 0
        self.restart_at = None
        self.served = 0
        self.heartbeats = 0
        self.last_stats = None
        self.hello = None
        self.drained = False
        # distributed observability plane
        self.last_beat_t = None      # monotonic time of the last beat
        self.ready_since = None      # start of the current live span
        self.live_s = 0.0            # completed live spans (dead gens)
        self.telemetry = None        # latest live TELEMETRY snapshot
        self.telemetry_t = None
        self.telemetry_frames = 0
        self.telemetry_covered_s = 0.0
        self.clock_offset = None     # latest generation's estimate
        self.clock_offsets = {}      # generation -> estimate (history)
        self.blackbox = None         # last exhumed black-box bundle

    @property
    def pid(self):
        return self.proc.pid if self.proc is not None else None


class _Entry:
    """Parent ledger row: one submitted request until terminal."""

    __slots__ = ("freq", "rid", "reroutes", "not_before", "failover",
                 "trace_ctx")

    def __init__(self, freq):
        self.freq = freq
        self.rid = None
        self.reroutes = 0
        self.not_before = 0.0
        self.failover = False
        self.trace_ctx = None


class ProcessFleet:
    """N worker processes behind a front-door router.

    :param spec: `make_worker_spec` output — the recipe workers rebuild
        their serving stack from
    :param n_workers: fleet size
    :param stream_spill: optional COMPLETE `utils.spill.SpillCache`
        holding the recorded stream; exported (`export_manifest`) into
        the spec so workers serve the shared L2 cross-process
    :param auto_restart: supervisor restarts dead workers with capped
        backoff (`restart_backoff_s` → `restart_backoff_max_s`, at most
        `max_restarts` times per worker slot)

    Lifecycle: ``start()`` (sweeps stale runs, spawns, waits ready) →
    ``submit(config).wait()`` / ``drain()`` → ``stop()``. The drill
    surface: ``kill_worker(rid, sig)``, ``set_control(rid, ...)``,
    ``publish_stream_state(...)``, ``worker(rid)``.
    """

    def __init__(self, spec, n_workers, *, stream_spill=None,
                 run_root=None,
                 lease_interval_s=0.02, miss_suspect=3, miss_revoke=6,
                 breaker_threshold=3, breaker_reopen_s=0.3,
                 breaker_max_reopen_s=4.0, half_open_probes=2,
                 restart_backoff_s=0.1, restart_backoff_max_s=2.0,
                 max_restarts=5, auto_restart=True,
                 request_deadline_s=None, boot_deadline_s=120.0,
                 frame_deadline_s=2.0, worker_trace=False):
        self.spec = dict(spec)
        self.spec["lease_interval_s"] = float(lease_interval_s)
        self.spec["trace"] = bool(worker_trace)
        self.worker_trace = bool(worker_trace)
        self.n_workers = int(n_workers)
        self.stream_spill = stream_spill
        self.run_root = run_root or fleet_run_root()
        self.lease_interval_s = float(lease_interval_s)
        self.miss_suspect = miss_suspect
        self.miss_revoke = miss_revoke
        self.breaker_threshold = breaker_threshold
        self.breaker_reopen_s = breaker_reopen_s
        self.breaker_max_reopen_s = breaker_max_reopen_s
        self.half_open_probes = half_open_probes
        self.restart_backoff_s = restart_backoff_s
        self.restart_backoff_max_s = restart_backoff_max_s
        self.max_restarts = max_restarts
        self.auto_restart = auto_restart
        self.request_deadline_s = request_deadline_s
        self.boot_deadline_s = boot_deadline_s
        self.frame_deadline_s = frame_deadline_s

        self.run_dir = None
        self._workers = {}
        self._pending = {}
        self._lock = threading.RLock()
        self._monitor = HealthMonitor(probe=self._probe,
                                      clock=time.monotonic)
        self._supervisor = None
        self._stopping = threading.Event()
        self._started = False
        self._lats = []
        self.counts = {
            "requests": 0, "served": 0, "shed": 0, "expired": 0,
            "failed": 0, "completed": 0, "failovers": 0, "reroutes": 0,
            "worker_deaths": 0, "restarts": 0, "orphans_reaped": 0,
            "stale_sockets_swept": 0, "heartbeats": 0,
            "telemetry_frames": 0, "telemetry_zombie": 0,
            "blackbox_exhumed": 0,
        }
        self._episodes = []  # [{"t0", "done", "failovers"}]
        self._tower = None
        # per-slot retired telemetry ledger: dead generations' final
        # counters/stages fold here (the cache fabric's drop_view
        # discipline) so fleet totals never regress on failover
        self._retired = {}

    # -- startup hygiene ----------------------------------------------------

    def _sweep_stale_runs(self):
        """Reap the wreckage of a crashed previous fleet: for every run
        dir whose owner pid is dead, kill still-running workers (pid
        from pidfile, verified against the cmdline marker so a recycled
        pid is never signalled) and remove stale socket files."""
        root = self.run_root
        if not os.path.isdir(root):
            return
        for name in os.listdir(root):
            rdir = os.path.join(root, name)
            if not os.path.isdir(rdir):
                continue
            try:
                with open(os.path.join(rdir, _FLEET_PIDFILE)) as fh:
                    owner = int(fh.read().strip())
            except (OSError, ValueError):
                owner = None
            if owner is not None and _pid_alive(owner):
                continue  # a live fleet owns this dir: hands off
            for entry in os.listdir(rdir):
                path = os.path.join(rdir, entry)
                if entry.endswith(".sock"):
                    try:
                        os.unlink(path)
                        self.counts["stale_sockets_swept"] += 1
                        _metrics.count("proc.stale_sockets_swept")
                    except OSError:
                        pass
                elif entry.startswith("worker-") and entry.endswith(".pid"):
                    try:
                        with open(path) as fh:
                            pid = int(fh.read().strip())
                    except (OSError, ValueError):
                        continue
                    if _pid_alive(pid) and _cmdline_matches(pid):
                        try:
                            os.kill(pid, signal.SIGKILL)
                            self.counts["orphans_reaped"] += 1
                            _metrics.count("proc.orphans_reaped")
                            log.warning(
                                "reaped orphaned worker pid %d from "
                                "stale run %s", pid, name)
                        except OSError:
                            pass
            shutil.rmtree(rdir, ignore_errors=True)

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        if self._started:
            raise RuntimeError("fleet already started")
        os.makedirs(self.run_root, exist_ok=True)
        self._sweep_stale_runs()
        self.run_dir = tempfile.mkdtemp(
            prefix=f"run-{os.getpid()}-", dir=self.run_root)
        with open(os.path.join(self.run_dir, _FLEET_PIDFILE), "w") as fh:
            fh.write(str(os.getpid()))
        if self.stream_spill is not None:
            manifest = self.stream_spill.export_manifest()
            self.spec["stream"] = manifest
            write_stream_state(
                os.path.join(self.run_dir, _STATE_FILE),
                stream_version=manifest["stream_version"])
        with open(os.path.join(self.run_dir, _SPEC_FILE), "wb") as fh:
            pickle.dump(self.spec, fh, protocol=pickle.HIGHEST_PROTOCOL)
        now = time.monotonic()
        for rid in range(self.n_workers):
            w = _Worker(rid)
            w.breaker = CircuitBreaker(
                name=f"worker-{rid}",
                failure_threshold=self.breaker_threshold,
                reopen_s=self.breaker_reopen_s,
                max_reopen_s=self.breaker_max_reopen_s,
                half_open_probes=self.half_open_probes,
                clock=time.monotonic,
            )
            self._workers[rid] = w
            self._spawn(w, now)
        self._started = True
        self._supervisor = threading.Thread(
            target=self._supervise, name="procfleet-supervisor",
            daemon=True)
        self._supervisor.start()
        self.wait_ready(self.boot_deadline_s)
        return self

    def _spawn(self, w, now):
        _fault_point("proc.spawn")
        w.generation += 1
        w.sock_path = os.path.join(
            self.run_dir, f"worker-{w.rid}.g{w.generation}.sock")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        logf = open(os.path.join(
            self.run_dir, f"worker-{w.rid}.g{w.generation}.log"), "wb")
        w.proc = subprocess.Popen(
            [sys.executable, "-m", WORKER_MARKER, "--worker",
             "--run-dir", self.run_dir, "--rid", str(w.rid),
             "--sock", w.sock_path, "--generation", str(w.generation)],
            stdout=logf, stderr=subprocess.STDOUT, env=env,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
        )
        logf.close()
        w.dead = False
        w.ready = False
        w.drained = False
        w.sock = None
        _metrics.count("proc.workers_spawned")
        _trace.instant("proc.worker_spawned", cat="proc",
                       rid=w.rid, pid=w.proc.pid, generation=w.generation)
        w.reader_thread = threading.Thread(
            target=self._attach_and_read, args=(w, w.generation),
            name=f"procfleet-reader-{w.rid}", daemon=True)
        w.reader_thread.start()

    def _attach_and_read(self, w, generation):
        """Connect to the worker's socket (retry ladder while it boots)
        then pump its frames: heartbeats beat the lease, results settle
        the ledger. Exits when the socket dies — the resulting silence
        is exactly how the lease learns the worker is gone."""
        try:
            sock = ipc.connect_unix(
                w.sock_path, deadline_s=self.boot_deadline_s)
        except OSError:
            return  # supervisor will see the silence
        with self._lock:
            if w.generation != generation or self._stopping.is_set():
                sock.close()
                return
            w.sock = sock
            w.wsock = sock.dup()
        t_hello = time.time()
        try:
            with w.send_lock:
                ipc.send_frame(w.wsock, ipc.FRAME_HELLO,
                               {"fleet_pid": os.getpid(),
                                "t_epoch": t_hello},
                               deadline_s=self.frame_deadline_s)
        except ipc.WireError:
            pass
        stream = ipc.FrameStream(sock)
        while not self._stopping.is_set():
            try:
                ftype, _flags, obj = stream.recv_frame(deadline_s=0.25)
            except ipc.WireDeadline:
                continue
            except (ipc.TruncatedFrame, ipc.WireError, OSError):
                break
            now = time.monotonic()
            if ftype == ipc.FRAME_HEARTBEAT:
                self._on_heartbeat(w, generation, obj, now)
            elif ftype == ipc.FRAME_RESULT:
                self._on_result(w, obj, now)
            elif ftype == ipc.FRAME_TELEMETRY:
                self._on_telemetry(w, generation, obj, now)
            elif ftype == ipc.FRAME_HELLO:
                self._on_hello(w, generation, obj, t_hello, time.time())
            elif ftype == ipc.FRAME_DRAIN:
                w.drained = True
            elif ftype == ipc.FRAME_ERROR:
                log.warning("worker %d wire error: %s",
                            w.rid, obj.get("error"))
        with self._lock:
            if w.generation == generation:
                w.sock = None

    def _on_heartbeat(self, w, generation, obj, now):
        self.counts["heartbeats"] += 1
        w.heartbeats += 1
        w.last_stats = obj
        _metrics.count("proc.heartbeats")
        with self._lock:
            if w.generation != generation:
                return
            w.last_beat_t = now
            if not w.ready:
                w.ready = True
                w.ready_since = now
                if w.lease is None:
                    w.lease = HealthLease(
                        f"worker-{w.rid}", self.lease_interval_s,
                        miss_suspect=self.miss_suspect,
                        miss_revoke=self.miss_revoke,
                        clock=time.monotonic,
                    )
                    self._monitor.register(w.rid, w.lease)
                elif w.lease.revoked:
                    self._monitor.revive(w.rid)
        w.lease.beat(now)

    @staticmethod
    def _clock_offset_from_hello(t_send, t_recv, hello):
        """NTP-style one-exchange offset estimate: the worker stamped
        its wall clock (``t_epoch``) somewhere inside the HELLO round
        trip, so assuming the midpoint, the worker's clock runs
        ``t_worker - (t_send + rtt/2)`` ahead of ours. Correct within
        ±rtt/2 — which is exactly why the RTT is recorded next to the
        offset and carried into the merged-trace manifest."""
        t_worker = (hello or {}).get("t_epoch")
        if not isinstance(t_worker, (int, float)):
            return None
        rtt = max(0.0, float(t_recv) - float(t_send))
        return {"offset_s": float(t_worker) - (float(t_send) + rtt / 2.0),
                "rtt_s": rtt}

    def _on_hello(self, w, generation, obj, t_send, t_recv):
        with self._lock:
            if w.generation != generation:
                return
            w.hello = obj
            off = self._clock_offset_from_hello(t_send, t_recv, obj)
            if off is not None:
                off["pid"] = (obj or {}).get("pid")
                off["generation"] = generation
                w.clock_offset = off
                w.clock_offsets[generation] = off

    def _on_telemetry(self, w, generation, obj, now):
        self.counts["telemetry_frames"] += 1
        _metrics.count("proc.telemetry_frames")
        with self._lock:
            if (not isinstance(obj, dict)
                    or w.generation != generation
                    or obj.get("generation", generation) != generation):
                # a zombie generation's snapshot (or garbage): counted,
                # never folded into the live slot's telemetry
                self.counts["telemetry_zombie"] += 1
                _metrics.count("proc.telemetry_zombie")
                return
            w.telemetry_frames += 1
            if w.telemetry_t is not None:
                # coverage accrual: the wall this frame vouches for,
                # capped so a stalled worker's late frame cannot claim
                # the stall as observed time
                gap = max(0.0, now - w.telemetry_t)
                w.telemetry_covered_s += min(
                    gap, 4 * self.lease_interval_s)
            w.telemetry = obj
            w.telemetry_t = now

    def _retire_telemetry(self, w):
        """Fold the dead generation's final telemetry snapshot into the
        per-slot retired ledger — the cache fabric's ``drop_view``
        discipline: a worker's counters outlive its process, so the
        fleet totals the tower sums NEVER regress on failover."""
        snap, w.telemetry = w.telemetry, None
        w.telemetry_t = None
        if not isinstance(snap, dict):
            return
        led = self._retired.setdefault(
            w.rid, {"counters": {}, "stages": {}, "generations": 0})
        led["generations"] += 1
        for name, v in (snap.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                led["counters"][name] = led["counters"].get(name, 0) + v
        for name, st in (snap.get("stages") or {}).items():
            if not isinstance(st, dict):
                continue
            agg = led["stages"].setdefault(
                name, {"count": 0, "total_s": 0.0})
            agg["count"] += int(st.get("count", 0) or 0)
            agg["total_s"] += float(st.get("total_s", 0.0) or 0.0)

    def _on_result(self, w, obj, now):
        req_id = obj["req_id"]
        with self._lock:
            entry = self._pending.get(req_id)
        if entry is None:
            return  # duplicate after a reroute: first result won
        res = RequestResult(
            obj["status"], data=obj["data"], error=obj["error"],
            latency_s=obj["latency_s"], path=obj["path"],
            retries=obj["retries"], shed_reason=obj["shed_reason"],
            retry_after_s=obj["retry_after_s"],
        )
        if res.status == STATUS_SHED and self._has_alternative(w.rid):
            # the worker's own admission door shed it but a survivor
            # can serve: reroute instead of surfacing the shed
            with self._lock:
                entry.rid = None
                entry.reroutes += 1
                entry.not_before = now + backoff_delay(
                    entry.reroutes, base_s=0.005, max_s=0.1)
            self.counts["reroutes"] += 1
            _metrics.count("proc.reroutes")
            return
        if res.ok:
            w.served += 1
            w.breaker.record_success(now)
            if w.lease is not None:
                w.lease.beat(now)  # a result is evidence of life
        self._finish(entry, res, now)

    def _finish(self, entry, res, now):
        with self._lock:
            if self._pending.pop(entry.freq.req_id, None) is None:
                return
            self.counts["completed"] += 1
            if res.ok:
                self.counts["served"] += 1
                _metrics.count("proc.served")
                lat = now - entry.freq.submit_t
                self._lats.append(lat)
                if len(self._lats) > _LAT_RING:
                    del self._lats[: _LAT_RING // 4]
            elif res.status == STATUS_SHED:
                self.counts["shed"] += 1
                _metrics.count("proc.shed")
            elif res.status == STATUS_EXPIRED:
                self.counts["expired"] += 1
                _metrics.count("proc.expired")
            else:
                self.counts["failed"] += 1
            if entry.failover and self._episodes:
                self._episodes[-1]["done"] = now
        if entry.trace_ctx is not None and _trace.enabled():
            # the router half of the cross-process request: duration-
            # derived endpoints keep this clock-safe even where
            # monotonic and perf_counter differ
            t1 = time.perf_counter()
            dur = max(0.0, now - entry.freq.submit_t)
            _trace.add_span(
                "proc.request", t1 - dur, t1, cat="proc",
                parent=entry.trace_ctx.get("span") or 0,
                req_id=entry.freq.req_id, rid=entry.rid,
                status=res.status, failover=entry.failover)
        entry.freq._complete(res)

    # -- routing ------------------------------------------------------------

    def _probe(self, rid):
        w = self._workers.get(rid)
        return (w is not None and not w.dead and w.proc is not None
                and w.proc.poll() is None and w.sock is not None)

    def _has_alternative(self, excluded_rid):
        now = time.monotonic()
        return any(
            self._routable(w, now) for w in self._workers.values()
            if w.rid != excluded_rid)

    def _routable(self, w, now):
        return (not w.dead and w.ready and w.sock is not None
                and w.lease is not None and not w.lease.revoked
                and w.breaker.allow(now))

    def _pick(self, off0, exclude, now):
        retry_transient(lambda: _fault_point("proc.route"),
                        site="proc.route", max_attempts=3, base_s=0.001)
        candidates = [
            w for w in self._workers.values()
            if w.rid not in exclude and self._routable(w, now)
        ]
        candidates.sort(
            key=lambda w: _rendezvous_score(off0, w.rid), reverse=True)
        return candidates[0] if candidates else None

    def submit(self, config, priority=0, deadline_s=None):
        """Route one request to a worker; returns a
        `serve.queue.SubgridRequest` handle (``wait()`` for the
        `RequestResult`). Never blocks: with no routable worker the
        request is parked in the ledger and the supervisor routes it
        the moment one recovers (or expires it at its deadline)."""
        if not self._started:
            raise RuntimeError("fleet not started")
        if deadline_s is None:
            deadline_s = self.request_deadline_s
        freq = SubgridRequest(config, priority=priority,
                              deadline_s=deadline_s)
        entry = _Entry(freq)
        if self.worker_trace and _trace.enabled():
            # the cross-process trace context REQUEST frames carry:
            # the router's current span + pid let the worker stamp
            # xparent/xpid, which merge_traces re-parents across the hop
            entry.trace_ctx = {"id": freq.req_id,
                               "span": _trace.current(),
                               "pid": os.getpid()}
        with self._lock:
            self._pending[freq.req_id] = entry
            self.counts["requests"] += 1
        _metrics.count("proc.requests")
        self._route(entry, time.monotonic())
        return freq

    def _route(self, entry, now, exclude=()):
        w = self._pick(entry.freq.config.off0, exclude, now)
        if w is None:
            # no routable worker right now: park; the supervisor
            # re-routes on its tick (capped by the request's deadline)
            with self._lock:
                entry.rid = None
                entry.not_before = now + backoff_delay(
                    entry.reroutes, base_s=0.01, max_s=0.25)
            return False
        remaining = None
        if entry.freq.deadline_t is not None:
            remaining = max(0.01, entry.freq.deadline_t
                            - time.perf_counter())
        payload = {
            "req_id": entry.freq.req_id,
            "config": entry.freq.config,
            "priority": entry.freq.priority,
            "deadline_s": remaining,
            "trace": entry.trace_ctx,
        }
        with self._lock:
            # claim BEFORE sending so the supervisor's scan can never
            # double-route this entry while the send is in flight
            entry.rid = w.rid
            wsock = w.wsock
        if wsock is None:
            with self._lock:
                entry.rid = None
            return self._route(entry, now, exclude=(*exclude, w.rid))
        try:
            with w.send_lock:
                ipc.send_frame(wsock, ipc.FRAME_REQUEST, payload,
                               deadline_s=self.frame_deadline_s)
        except (ipc.WireError, OSError) as exc:
            # a failed send may have left a partial frame: the stream
            # is indeterminate, so the connection is dead — drop it and
            # let the lease's silence drive reap + restart
            w.breaker.record_failure(time.monotonic(), reason=repr(exc))
            self._drop_connection(w)
            with self._lock:
                entry.rid = None
                entry.reroutes += 1
            return self._route(entry, now, exclude=(*exclude, w.rid))
        return True

    def _drop_connection(self, w):
        with self._lock:
            sock, w.sock = w.sock, None
            wsock, w.wsock = w.wsock, None
        for s in (sock, wsock):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    # -- supervision --------------------------------------------------------

    def _supervise(self):
        tick = max(0.005, self.lease_interval_s / 2)
        while not self._stopping.wait(tick):
            now = time.monotonic()
            try:
                for rid, _frm, to in self._monitor.check(now):
                    if to == "revoked":
                        self._on_revoked(rid, now)
                self._scan(now)
                self._restart_due(now)
                if self._tower is not None:
                    self._tower.tick(now)
            except Exception:  # pragma: no cover - supervisor must live
                log.exception("supervisor tick failed")

    def _on_revoked(self, rid, now):
        w = self._workers.get(rid)
        if w is None or w.dead:
            return
        w.dead = True
        self.counts["worker_deaths"] += 1
        _metrics.count("proc.worker_deaths")
        w.breaker.trip(now, reason="lease_revoked")
        _trace.instant("proc.worker_death", cat="proc", rid=rid,
                       pid=w.pid, generation=w.generation)
        _recorder.record("proc", "proc.worker_death",
                         f"rid={rid} pid={w.pid}")
        # reap: kill if somehow still alive (silent socket, live
        # process), then waitpid so no zombie accumulates
        if w.proc is not None:
            if w.proc.poll() is None:
                try:
                    w.proc.kill()
                except OSError:
                    pass
            try:
                w.proc.wait(timeout=5.0)
            except Exception:
                pass
        self._drop_connection(w)
        with self._lock:
            if w.ready_since is not None:
                w.live_s += max(0.0, now - w.ready_since)
                w.ready_since = None
            self._retire_telemetry(w)
        self._exhume_worker(w)
        # fail the dead worker's in-flight ledger rows over
        failovers = 0
        with self._lock:
            for entry in self._pending.values():
                if entry.rid == rid:
                    entry.rid = None
                    entry.failover = True
                    entry.reroutes += 1
                    entry.not_before = now
                    failovers += 1
            self._episodes.append(
                {"t0": now, "done": None, "failovers": failovers})
        if failovers:
            self.counts["failovers"] += failovers
            _metrics.count("proc.failovers", failovers)
        if self.auto_restart and w.restarts < self.max_restarts:
            w.restart_at = now + backoff_delay(
                w.restarts, base_s=self.restart_backoff_s,
                max_s=self.restart_backoff_max_s)

    def _exhume_worker(self, w):
        """Dig up the dead worker's black box and fold its event tail
        into the PARENT's flight recorder: the next post-mortem shows
        what the victim itself saw in its last seconds — the L2 dwell
        it held, the request it was serving — not just the router's
        outside view of the silence."""
        try:
            box = exhume_blackbox(self.run_dir, w.rid,
                                  max_generation=w.generation)
        except Exception:  # pragma: no cover - exhumation best-effort
            log.exception("black-box exhumation failed for rid %d",
                          w.rid)
            return
        if box is None:
            return
        w.blackbox = box
        self.counts["blackbox_exhumed"] += 1
        _metrics.count("proc.blackbox_exhumed")
        _recorder.record(
            "proc", "proc.blackbox_exhumed",
            f"rid={w.rid} g={box['generation']} "
            f"events={box['n_events']}"
            + (" torn_index" if box.get("torn_index") else ""))
        tail = [e for e in box["events"]
                if isinstance(e, dict) and e.get("kind") != "stage"][-32:]
        for e in tail:
            detail = e.get("detail")
            _recorder.record(
                e.get("kind", "proc"), str(e.get("name", "?")),
                f"[worker-{w.rid} g{box['generation']} t={e.get('t')}]"
                + ("" if detail is None else f" {detail}"))

    def _scan(self, now):
        with self._lock:
            entries = list(self._pending.values())
        for entry in entries:
            if entry.freq.done:
                continue
            if entry.freq.expired(time.perf_counter()):
                self._finish(entry, RequestResult(
                    STATUS_EXPIRED, error="deadline passed",
                    latency_s=now - entry.freq.submit_t), now)
                continue
            rid = entry.rid
            if rid is not None:
                w = self._workers.get(rid)
                if w is not None and w.dead:
                    with self._lock:
                        entry.rid = None
                        entry.failover = True
                        entry.reroutes += 1
                    rid = None
            if rid is None and now >= entry.not_before:
                self._route(entry, now)

    def _restart_due(self, now):
        for w in self._workers.values():
            if w.dead and w.restart_at is not None and now >= w.restart_at:
                w.restart_at = None
                w.restarts += 1
                self.counts["restarts"] += 1
                _metrics.count("proc.restarts")
                _trace.instant("proc.worker_restarted", cat="proc",
                               rid=w.rid, restarts=w.restarts)
                _recorder.record("proc", "proc.worker_restarted",
                                 f"rid={w.rid} restarts={w.restarts}")
                # trips persist: the restarted worker re-earns trust
                # through the breaker's half-open probe path
                self._spawn(w, now)

    # -- distributed observability plane ------------------------------------

    def register_tower(self, tower, *, slos=True, queue_depth_limit=None,
                       failover_budget_ms=1000.0):
        """Plug the fleet into an `obs.tower.ControlTower`: one
        ``router`` source (the parent's ledger counters), one
        ``worker-<rid>`` source per slot (live TELEMETRY snapshot +
        the retired ledger, so totals survive failover), the fleet
        signals (``proc.heartbeat_gap_s``, ``proc.queue_depth``,
        ``proc.failover_ms``) and — unless ``slos=False`` — the
        matching burn-rate SLOs (``proc_heartbeat_gap``,
        ``proc_queue_depth``, ``proc_failover``). The supervisor ticks
        the tower once registered, so sampling shares the fleet's
        supervision clock."""
        self._tower = tower
        tower.register_source("router", self._router_source,
                              kind="router")
        for rid in range(self.n_workers):
            tower.register_source(
                f"worker-{rid}",
                (lambda r=rid: self._worker_source(r)),
                kind="worker")
        tower.register_signal("proc.heartbeat_gap_s",
                              self._signal_heartbeat_gap)
        tower.register_signal(
            "proc.queue_depth", lambda: float(len(self._pending)))
        tower.register_signal("proc.failover_ms",
                              self._signal_failover_ms)
        if slos:
            fast = max(0.2, 10 * self.lease_interval_s)
            slow = 3 * fast
            if queue_depth_limit is None:
                queue_depth_limit = 8 * self.n_workers
            tower.add_slo(SLO(
                "proc_heartbeat_gap", "proc.heartbeat_gap_s",
                threshold=self.miss_revoke * self.lease_interval_s,
                direction="above", fast_s=fast, slow_s=slow, burn=0.5))
            tower.add_slo(SLO(
                "proc_queue_depth", "proc.queue_depth",
                threshold=float(queue_depth_limit),
                direction="above", fast_s=fast, slow_s=slow, burn=0.5))
            tower.add_slo(SLO(
                "proc_failover", "proc.failover_ms",
                threshold=float(failover_budget_ms),
                direction="above", fast_s=fast, slow_s=slow, burn=0.5))
        return tower

    def _router_source(self):
        """The parent's own telemetry source: ledger counters under a
        ``proc.router.`` prefix so they never collide with the workers'
        in-process ``proc.*`` metric names."""
        with self._lock:
            counters = {f"proc.router.{k}": v
                        for k, v in self.counts.items()}
        return {"counters": counters, "pid": os.getpid()}

    def _worker_source(self, rid):
        """One slot's telemetry source: the retired ledger (every dead
        generation's final snapshot) plus the live generation's latest
        TELEMETRY frame — monotone across restarts by construction."""
        w = self._workers.get(rid)
        with self._lock:
            led = self._retired.get(rid) or {}
            counters = dict(led.get("counters") or {})
            stages = {name: dict(st)
                      for name, st in (led.get("stages") or {}).items()}
            snap = w.telemetry if w is not None else None
            if isinstance(snap, dict):
                for name, v in (snap.get("counters") or {}).items():
                    if isinstance(v, (int, float)):
                        counters[name] = counters.get(name, 0) + v
                for name, st in (snap.get("stages") or {}).items():
                    if not isinstance(st, dict):
                        continue
                    agg = stages.setdefault(
                        name, {"count": 0, "total_s": 0.0})
                    agg["count"] += int(st.get("count", 0) or 0)
                    agg["total_s"] += float(st.get("total_s", 0.0) or 0.0)
        return {
            "counters": counters,
            "stages": stages,
            "pid": w.pid if w is not None else None,
            "generation": w.generation if w is not None else 0,
            "alive": bool(w is not None and not w.dead),
            "retired_generations": int(led.get("generations", 0)),
            "telemetry_frames": w.telemetry_frames if w is not None
            else 0,
            "last_stats": w.last_stats if w is not None else None,
        }

    def _signal_heartbeat_gap(self):
        """Seconds since the quietest live worker's last heartbeat —
        the wire-level liveness signal the SLO watches."""
        now = time.monotonic()
        with self._lock:
            gaps = [now - w.last_beat_t for w in self._workers.values()
                    if not w.dead and w.last_beat_t is not None]
        return max(gaps) if gaps else 0.0

    def _signal_failover_ms(self):
        """The latest COMPLETED failover episode's duration (0 with
        none yet) — burns the ``proc_failover`` SLO when recovery
        blows its budget."""
        with self._lock:
            for ep in reversed(self._episodes):
                if ep["done"] is not None and ep["failovers"]:
                    return (ep["done"] - ep["t0"]) * 1e3
        return 0.0

    def telemetry_coverage(self, now=None):
        """Fraction of worker live-seconds vouched for by TELEMETRY
        frames (clamped to [0, 1]; None before any worker went live).
        The ``procfleet.telemetry_coverage`` bench sentinel: a wire
        regression that drops frames shows up here before anyone
        misses the data."""
        now = time.monotonic() if now is None else now
        covered = 0.0
        live = 0.0
        with self._lock:
            for w in self._workers.values():
                covered += w.telemetry_covered_s
                live += w.live_s
                if w.ready_since is not None and not w.dead:
                    live += max(0.0, now - w.ready_since)
        if live <= 0.0:
            return None
        return max(0.0, min(1.0, covered / live))

    def merged_trace(self, labels=None):
        """ONE Perfetto timeline for the whole fleet: the router's own
        trace as the time base, every worker generation's atomically
        published timeline shifted onto it using the HELLO clock
        offsets (`obs.report.merge_traces`). Call BEFORE `stop()` —
        workers publish into the run dir, which stop() removes."""
        from ..obs.report import merge_traces

        traces = [_trace.export()]
        offsets = {}
        label_map = {os.getpid(): "router"}
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            for g, off in sorted(w.clock_offsets.items()):
                pid = off.get("pid")
                if pid is not None:
                    offsets[pid] = off
                    label_map.setdefault(pid, f"worker-{w.rid}.g{g}")
            for g in range(1, w.generation + 1):
                path = os.path.join(self.run_dir,
                                    f"trace-{w.rid}.g{g}.json")
                try:
                    with open(path) as fh:
                        traces.append(json.load(fh))
                except (OSError, ValueError):
                    continue
        if labels:
            label_map.update(labels)
        return merge_traces(traces, offsets=offsets, labels=label_map)

    def heartbeat_fields(self):
        """The fleet fields `obs.heartbeat.Heartbeat` stamps when a
        ProcessFleet rides along on a beat: live worker count, summed
        worker generations, open tower alerts (None without a tower)."""
        with self._lock:
            alive = sum(1 for w in self._workers.values() if not w.dead)
            gens = sum(w.generation for w in self._workers.values())
        return {
            "proc_workers": alive,
            "worker_generations": gens,
            "proc_open_alerts": (
                len(self._tower.open_alerts())
                if self._tower is not None else None),
        }

    # -- drill / operator surface -------------------------------------------

    def worker(self, rid):
        return self._workers[rid]

    def kill_worker(self, rid, sig=signal.SIGKILL):
        """Signal a worker process — the drill's real kill. Returns the
        signalled pid."""
        w = self._workers[rid]
        pid = w.pid
        os.kill(pid, sig)
        return pid

    def set_control(self, rid, **payload):
        """Send a ``CONTROL`` frame (e.g. ``dwell_l2_s=0.5`` arms the
        mid-L2-read kill window)."""
        w = self._workers[rid]
        with w.send_lock:
            ipc.send_frame(w.wsock, ipc.FRAME_CONTROL, payload,
                           deadline_s=self.frame_deadline_s)

    def dwell_flag_path(self, rid):
        return os.path.join(self.run_dir, f"l2_dwell_{rid}.flag")

    def publish_stream_state(self, *, stream_version=None, complete=True,
                             patching=False):
        """Re-stamp the cross-process stream-state file — flipping
        ``patching`` or bumping ``stream_version`` makes every worker's
        feed refuse (and recompute) on its next lookup, the same gates
        the in-process feed enforces."""
        if stream_version is None:
            stream_version = (self.spec.get("stream") or {}).get(
                "stream_version", 0)
        write_stream_state(
            os.path.join(self.run_dir, _STATE_FILE),
            stream_version=stream_version, complete=complete,
            patching=patching)

    def wait_ready(self, timeout_s=60.0, n=None):
        """Block until ``n`` (default: all) workers are ready."""
        need = self.n_workers if n is None else n
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if sum(1 for w in self._workers.values()
                   if w.ready and not w.dead) >= need:
                return True
            time.sleep(0.01)
        return False

    def drain(self, timeout_s=30.0):
        """Wait for every ledger row to reach a terminal state."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending:
                    return True
            time.sleep(0.005)
        return False

    def stop(self, drain=True):
        if drain:
            self.drain()
        self._stopping.set()
        for w in self._workers.values():
            if w.wsock is not None and not w.dead:
                try:
                    with w.send_lock:
                        ipc.send_frame(w.wsock, ipc.FRAME_DRAIN, {},
                                       deadline_s=0.5)
                except (ipc.WireError, OSError):
                    pass
        for w in self._workers.values():
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=5.0)
                except Exception:
                    try:
                        w.proc.kill()
                        w.proc.wait(timeout=2.0)
                    except Exception:
                        pass
            self._drop_connection(w)
        if self._supervisor is not None:
            self._supervisor.join(timeout=2.0)
        if self.run_dir is not None:
            shutil.rmtree(self.run_dir, ignore_errors=True)

    # -- stats ---------------------------------------------------------------

    def lost_requests(self):
        """Requests that never reached a terminal state and are no
        longer in the ledger — the zero-loss drill's headline number
        (0 or the drill failed). Requests still pending are not lost
        yet; drain first."""
        with self._lock:
            return (self.counts["requests"] - self.counts["completed"]
                    - len(self._pending))

    def stats(self, wall_s=None):
        with self._lock:
            lats = sorted(self._lats)
            pending = len(self._pending)
            episodes = [dict(e) for e in self._episodes]

        def q(p):
            if not lats:
                return 0.0
            return lats[min(len(lats) - 1, int(p * len(lats)))] * 1e3

        failover_ms = None
        for ep in episodes:
            if ep["done"] is not None and ep["failovers"]:
                ms = (ep["done"] - ep["t0"]) * 1e3
                failover_ms = ms if failover_ms is None else max(
                    failover_ms, ms)
        out = {
            "n_workers": self.n_workers,
            "pending": pending,
            "lost_requests": (self.counts["requests"]
                              - self.counts["completed"] - pending),
            "p50_ms": q(0.50),
            "p99_ms": q(0.99),
            "failover_ms": failover_ms,
            "failover_episodes": [
                {"failovers": ep["failovers"],
                 "ms": None if ep["done"] is None
                 else (ep["done"] - ep["t0"]) * 1e3}
                for ep in episodes
            ],
            **self.counts,
            "health": self._monitor.stats(),
            "breakers": {
                w.rid: w.breaker.stats() for w in self._workers.values()
            },
            "telemetry": {
                "frames": self.counts["telemetry_frames"],
                "zombie_frames": self.counts["telemetry_zombie"],
                "coverage": self.telemetry_coverage(),
                "retired_generations": sum(
                    led.get("generations", 0)
                    for led in self._retired.values()),
            },
            "clock_offsets": {
                str(w.rid): dict(w.clock_offset)
                for w in self._workers.values()
                if w.clock_offset is not None
            },
            "black_box": {
                "exhumed": [
                    {"rid": w.rid,
                     "generation": w.blackbox["generation"],
                     "n_events": w.blackbox["n_events"],
                     "torn_index": bool(w.blackbox.get("torn_index"))}
                    for w in self._workers.values()
                    if w.blackbox is not None
                ],
            },
            "per_worker": [
                {
                    "id": w.rid,
                    "pid": w.pid,
                    "alive": not w.dead,
                    "generation": w.generation,
                    "restarts": w.restarts,
                    "served": w.served,
                    "heartbeats": w.heartbeats,
                    "telemetry_frames": w.telemetry_frames,
                    "clock_offset": w.clock_offset,
                    "last_stats": w.last_stats,
                    "qps": (w.served / wall_s) if wall_s else None,
                }
                for w in self._workers.values()
            ],
        }
        return out


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _cmdline_matches(pid, marker=WORKER_MARKER):
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as fh:
            cmdline = fh.read().replace(b"\x00", b" ").decode(
                "utf-8", "replace")
    except OSError:
        return False
    return marker in cmdline and "--worker" in cmdline


def main(argv=None):
    """``python -m swiftly_tpu.serve.procfleet --worker ...`` — the
    worker-process entry the parent spawns."""
    import argparse

    parser = argparse.ArgumentParser(prog="swiftly_tpu.serve.procfleet")
    parser.add_argument("--worker", action="store_true", required=True)
    parser.add_argument("--run-dir", required=True)
    parser.add_argument("--rid", type=int, required=True)
    parser.add_argument("--sock", required=True)
    parser.add_argument("--generation", type=int, default=1)
    args = parser.parse_args(argv)
    return _worker_main(args.run_dir, args.rid, args.sock,
                        generation=args.generation)


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(main())
