"""Pricing the visibility-serving path (`plan.price_vis`).

The one free parameter of `vis.service.VisibilityService`'s dispatch
shape is the scheduler's ``max_batch`` — how many coalesced samples one
degrid program answers. Small batches pay the per-dispatch overhead
per few samples; large ones pad harder (power-of-two buckets,
`vis.degrid.bucket_size`) and wait longer to fill. `price_vis` scans
the power-of-two candidates with the SAME `plan.model
.CostCoefficients` the rest of the compiler prices with:

* ``vis.row_fetch`` — one row read per dispatch, blended between the
  cache feed's L1 rate and the spill read rate at the expected hit
  rate (the serve cache fabric's tiering, `plan.price_cache_tier`);
* ``vis.degrid`` / ``vis.grid`` — the batch contraction, flops/bytes
  attributed exactly as `vis.service` / `vis.grid.VisGridder` record
  them, so `plan.autotune.refit` refits these stages from any recorded
  ``bench.py --vis`` artifact and the next plan prices with measured
  rates (``coeffs_source`` records the pedigree).

Every scanned candidate is kept in ``alternatives``
(`scripts/plan_explain.py --vis` prints the table), matching
`compile_plan`'s alternative-recording contract.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .model import CostCoefficients, StageCost

__all__ = ["VisPlan", "price_vis"]

# flops/bytes attribution per padded sample lane, shared with the
# recording sites (vis.service._serve_subgrid, vis.grid.VisGridder):
# 2 planes x (W*W multiply-adds) + the [B, W, W] weight outer product
DEGRID_FLOPS_PER_LANE = 6  # x W^2
DEGRID_BYTES_PER_LANE = 8  # x W^2 (two gathered f32 patch planes)


@dataclass
class VisPlan:
    """Priced visibility-serving dispatch shape.

    ``max_batch`` is the chosen coalescing cap (power-of-two, so the
    bucket pad is the identity); ``predicted`` holds the per-stage
    `plan.model.StageCost` dicts for the chosen shape and
    ``alternatives`` every scanned candidate (``chosen`` flags).
    """

    n_samples: int
    support: int
    subgrid_size: int
    cache_hit_rate: float
    max_batch: int
    predicted_wall_s: float
    throughput_ksamples_s: float
    predicted: dict = field(default_factory=dict)
    alternatives: list = field(default_factory=list)
    coeffs_source: str = "default"

    def as_dict(self):
        return {
            "n_samples": int(self.n_samples),
            "support": int(self.support),
            "subgrid_size": int(self.subgrid_size),
            "cache_hit_rate": round(float(self.cache_hit_rate), 4),
            "max_batch": int(self.max_batch),
            "predicted_wall_s": round(float(self.predicted_wall_s), 6),
            "throughput_ksamples_s": round(
                float(self.throughput_ksamples_s), 3
            ),
            "predicted": {
                k: v.as_dict() for k, v in self.predicted.items()
            },
            "coeffs_source": self.coeffs_source,
            "alternatives": list(self.alternatives),
        }

    def explain(self):
        """Human-readable candidate table
        (``scripts/plan_explain.py --vis``)."""
        lines = [
            f"vis plan: {self.n_samples} samples, support "
            f"{self.support}, subgrid {self.subgrid_size}, cache hit "
            f"rate {self.cache_hit_rate:.2f} -> max_batch "
            f"{self.max_batch} "
            f"({self.predicted_wall_s * 1e3:.2f} ms predicted, "
            f"{self.throughput_ksamples_s:.1f} ksamples/s, "
            f"{self.coeffs_source} coefficients)",
            "  max_batch  dispatches  wall_ms  ksamples_s  choice",
        ]
        for alt in self.alternatives:
            mark = " *" if alt.get("chosen") else ""
            lines.append(
                f"  {alt['max_batch']:>9}  "
                f"{alt['dispatches']:>10}  "
                f"{alt['wall_ms']:>7.2f}  "
                f"{alt['ksamples_s']:>10.1f}{mark}"
            )
        return "\n".join(lines)


def price_vis(n_samples, subgrid_size, support=8, cache_hit_rate=0.0,
              include_grid=False, coeffs=None, history=None,
              candidates=None):
    """Price a visibility workload and pick the coalescing cap.

    :param n_samples: expected samples per pump window
    :param subgrid_size: served row size (``xA``)
    :param support: kernel tap count (`vis.kernel.VisKernel.support`)
    :param cache_hit_rate: expected feed hit rate in [0, 1] — splits
        the per-dispatch row read between ``cache.l1`` and
        ``spill.read`` pricing
    :param include_grid: also price the adjoint accumulation
        (``vis.grid``) into the wall — the gridding ingest workload
    :param coeffs: `plan.model.CostCoefficients`; with ``history``
        given, refit from recorded artifacts instead
        (`plan.autotune.refit` — the vis stages record attributed
        flops, so measured rates supersede the anchors)
    :param candidates: max-batch candidates to scan (default powers of
        two 16..4096)
    :return: `VisPlan`
    """
    if coeffs is None:
        if history:
            from .autotune import refit

            coeffs = refit(history)
        else:
            coeffs = CostCoefficients()
    n = max(1, int(n_samples))
    W = int(support)
    hit = min(1.0, max(0.0, float(cache_hit_rate)))
    row_bytes = 2 * int(subgrid_size) ** 2 * 4
    if candidates is None:
        candidates = [1 << i for i in range(4, 13)]  # 16 .. 4096

    def stage_costs(m):
        n_disp = -(-n // m)  # ceil
        lanes = n_disp * m  # power-of-two m: bucket pad == m
        # one priced row-fetch stage, hit/miss tiers blended at the
        # expected hit rate (the runtime times it as one stage too)
        fetch_bytes = n_disp * row_bytes
        fetch_wall = (
            hit * fetch_bytes / coeffs.bytes_rate("cache.l1")
            + (1 - hit) * fetch_bytes / coeffs.bytes_rate("spill.read")
        )
        costs = {
            "vis.row_fetch": StageCost(
                "vis.row_fetch", 0, int(fetch_bytes), n_disp,
                fetch_wall,
            ),
            "vis.degrid": coeffs.price(
                "vis.degrid",
                flops=DEGRID_FLOPS_PER_LANE * lanes * W * W,
                bytes_moved=DEGRID_BYTES_PER_LANE * lanes * W * W,
                dispatches=n_disp,
            ),
        }
        if include_grid:
            costs["vis.grid"] = coeffs.price(
                "vis.grid",
                flops=8 * lanes * W * W,
                bytes_moved=DEGRID_BYTES_PER_LANE * lanes * W * W,
                dispatches=n_disp,
            )
        return n_disp, costs

    alternatives, best = [], None
    for m in candidates:
        n_disp, costs = stage_costs(m)
        wall = sum(c.wall_s for c in costs.values())
        alternatives.append({
            "max_batch": m,
            "dispatches": n_disp,
            "wall_ms": round(wall * 1e3, 3),
            "ksamples_s": round(n / wall / 1e3, 1) if wall else 0.0,
            "chosen": False,
        })
        if best is None or wall < best[1]:
            best = (m, wall, n_disp, costs)
    m, wall, n_disp, costs = best
    for alt in alternatives:
        alt["chosen"] = alt["max_batch"] == m
    return VisPlan(
        n_samples=n,
        support=W,
        subgrid_size=int(subgrid_size),
        cache_hit_rate=hit,
        max_batch=m,
        predicted_wall_s=wall,
        throughput_ksamples_s=(n / wall / 1e3) if wall else 0.0,
        predicted=costs,
        alternatives=alternatives,
        coeffs_source=coeffs.source,
    )
