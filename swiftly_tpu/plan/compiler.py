"""`compile_plan`: search the cost model, emit ONE executable plan.

The plan is the single source for the four decisions that used to be
priced independently (ROADMAP item 4):

* the backward facet x output-row-slab pass grid
  (`plan_backward_passes` — moved here verbatim from bench.py; bench
  now delegates, and the 4k/32k/64k/128k golden tests pin equality)
  plus its feed-once/fold-many schedule (`plan_backward_feed`: how
  many passes share each pass over the subgrid stream under the HBM
  budget — the grid is grouped, never changed);
* the spill policy (RAM ring / disk backing / forward replay) for the
  subgrid stream every backward pass consumes;
* the serve batch shapes (power-of-two buckets under the coalescing
  cap) and the admission byte projections;
* the forward column/facet grouping PREDICTION (reusing the calibrated
  `parallel.streamed` sizers through the geometry shim — the executors
  keep making the binding choice at dispatch time, so a plan is
  explainable without a device but never forks the transient
  accounting).

Plus the `MeshLayout`: the mesh shape falls out of the same model
(arXiv 2002.03260) — `plan_mesh_layout` shards the facet axis over the
planned device count, prices per-shard HBM and ICI collective bytes,
and the mesh-streamed engine (`swiftly_tpu.mesh`) binds the layout at
construction (``status: "stub"`` → ``"bound"``).

Selection policy: with DEFAULT coefficients the compiler keeps the seed
heuristics' choices (provable equivalence); with MEASURED coefficients
(`compile_plan(..., history=...)` -> `autotune.refit`) it picks e.g.
the fold group by predicted wall, and records every evaluated
alternative so `scripts/plan_explain.py` can show what was rejected and
why.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from .model import (
    CostCoefficients,
    DEFAULT_FWD_MIN_BYTES,
    DEFAULT_RESERVE_BYTES,
    PlanInputs,
    bucket_sizes,
    price_backward,
    price_forward,
)

__all__ = [
    "BackwardPlan",
    "CacheTierPlan",
    "DeltaPlan",
    "MeshLayout",
    "Plan",
    "ServePlan",
    "SpillPolicy",
    "compile_plan",
    "plan_backward_feed",
    "plan_backward_passes",
    "plan_delta",
    "plan_mesh_layout",
    "price_cache_tier",
    "stamp_measured_wall",
]

PLAN_SCHEMA = "swiftly-tpu-plan/1"


def stamp_measured_wall(block, measured_wall_s):
    """Close a stamped plan block with its measured wall.

    Sig-fig rounding, not decimal: ``round(x, 4)`` zeroed sub-0.1 ms
    smoke-scale legs and the falsy ``0.0`` then silently dropped the
    ratio — bench_compare skipped the leg as non-comparable. The ratio
    (predicted / measured) is emitted whenever both walls are genuinely
    positive. Shared by `Plan.artifact_block` and bench's leg close.
    """
    from ..obs.ledger import round_sig

    measured = float(measured_wall_s)
    block["measured_wall_s"] = round_sig(measured)
    pred = (block.get("predicted") or {}).get("wall_s") or 0
    if pred > 0 and measured > 0:
        block["predicted_vs_measured"] = round_sig(pred / measured)
    return block

# Fold groups the measured-coefficient search ranks (the seed default 2
# is always among them; larger groups trade dispatch count against the
# fold pipeline's resident rows, which is exactly the axis the history
# can price).
_FOLD_GROUP_CANDIDATES = (1, 2, 4, 8)


def plan_backward_passes(
    F_total, yB, per_facet_acc, per_facet_rows, fold_group, budget,
    fwd_min=DEFAULT_FWD_MIN_BYTES, reserve=DEFAULT_RESERVE_BYTES,
    n_facet_env=0, n_row_env=0,
):
    """Facet x output-row-slab partition plan for the sampled backward.

    Returns ``(parts, resident_bytes)``: `parts` is the pass list
    [(i0, i1, r0, r1), ...] — facet subset [i0, i1) x accumulator rows
    [r0, r1) — and `resident_bytes` the largest pass's accumulator +
    row-pipeline residency (what the forward's auto-sizers must leave
    free, `fwd.hbm_headroom`).

    Partition order: facets first (the 64k mechanism — single-facet
    passes leave the shared subgrid stream the most headroom), then
    output-row slabs within a facet once even ONE facet's accumulator
    exceeds the per-pass budget (the 128k mechanism: one 45056^2 facet
    is 16.2 GiB; the fold's "ri" index restricts trivially, see
    `StreamedBackward(row_slab=...)`). Every pass consumes the SAME
    subgrid stream, so with the spill cache the total cost is one
    forward + len(parts) cache-fed backward passes.

    (Moved verbatim from ``bench._plan_backward_passes``; bench
    delegates here and tests/test_128k.py pins the equivalence.)

    :param per_facet_acc: one facet's WHOLE [yB, yB] accumulator bytes
    :param per_facet_rows: one facet's [m, yB] column-rows bytes (the
        fold pipeline keeps 2*fold_group + 2 of these live per facet)
    :param budget: per-device HBM bytes (None = unpartitioned, e.g. CPU)
    :param n_facet_env / n_row_env: operator overrides
        (BENCH_BWD_FACET_PASSES / BENCH_BWD_ROW_SLABS)
    """
    rows_resident = (2 * fold_group + 2) * per_facet_rows
    usable = None if budget is None else budget - fwd_min - reserve
    if n_facet_env:
        n_parts = max(1, min(int(n_facet_env), F_total))
    elif usable is None:
        n_parts = 1
    elif F_total * (per_facet_acc + rows_resident) <= usable:
        n_parts = 1
    else:
        # once partitioning is forced, single-facet passes win: the
        # stream feed dominates each pass and its sizing scales with
        # the headroom the accumulator leaves (measured at 64k)
        n_parts = F_total
    F_sub = -(-F_total // n_parts)
    n_row = 1
    if n_row_env:
        n_row = max(1, min(int(n_row_env), yB))
    elif usable is not None and n_parts > 1:
        per_pass = F_sub * (per_facet_acc + rows_resident)
        if per_pass > usable:
            # slab the accumulator; the column rows stay full-width
            # (the fold consumes every row whatever slab it outputs)
            acc_budget = usable - F_sub * rows_resident
            per_row = max(1.0, F_sub * per_facet_acc / yB)
            h = int(acc_budget // per_row) if acc_budget > 0 else 0
            n_row = -(-yB // max(1, h))
    row_h = -(-yB // n_row)
    parts = [
        (i0, min(i0 + F_sub, F_total), r0, min(r0 + row_h, yB))
        for i0 in range(0, F_total, F_sub)
        for r0 in range(0, yB, row_h)
    ]
    resident = max(
        (i1 - i0) * (per_facet_acc * (r1 - r0) / yB + rows_resident)
        for i0, i1, r0, r1 in parts
    )
    return parts, int(resident)


def plan_backward_feed(
    parts, resident_per_pass, budget,
    fwd_min=DEFAULT_FWD_MIN_BYTES, reserve=DEFAULT_RESERVE_BYTES,
    feed_env=0,
):
    """Passes-per-feed for the feed-once/fold-many backward schedule.

    ``q`` passes sharing one feed keep ``q`` image accumulators (and
    their fold-row pipelines) resident at once next to the feed's
    working set, and in exchange the subgrid stream crosses the wire
    once per FEED instead of once per pass
    (`parallel.streamed.feed_backward_passes`) — with P passes the h2d
    traffic drops from P× to ceil(P/q)× the stream. So q is simply the
    largest pass count whose summed residency fits the per-pass HBM
    budget the pass grid itself was sized against
    (``budget − fwd_min − reserve``); the grid (`plan_backward_passes`)
    is unchanged — n_passes semantics are preserved, the schedule only
    groups the passes.

    :param resident_per_pass: the grid's largest per-pass residency
        (`plan_backward_passes`' second return)
    :param feed_env: operator override (bench's BENCH_BWD_FEED_GROUP)
    :returns: passes per feed, in [1, len(parts)]
    """
    n_passes = len(parts)
    if feed_env:
        return max(1, min(int(feed_env), n_passes))
    if n_passes <= 1:
        return 1
    if budget is None:
        return n_passes  # unlimited (CPU): one feed serves every pass
    usable = budget - fwd_min - reserve
    if resident_per_pass <= 0:
        return n_passes
    return max(1, min(int(usable // resident_per_pass), n_passes))


# ---------------------------------------------------------------------------
# Plan components
# ---------------------------------------------------------------------------


@dataclass
class BackwardPlan:
    parts: list
    fold_group: int
    resident_bytes: int
    feed_group: int = 1  # passes sharing one stream feed

    @property
    def n_passes(self):
        return len(self.parts)

    @property
    def n_facet_passes(self):
        return len({(p[0], p[1]) for p in self.parts})

    @property
    def n_row_slabs(self):
        return len({(p[2], p[3]) for p in self.parts})

    @property
    def n_feeds(self):
        return -(-self.n_passes // max(1, self.feed_group))

    def feed_chunks(self):
        """The pass list chunked by the feed schedule: each chunk is
        the group of parts one `feed_backward_passes` call serves."""
        q = max(1, self.feed_group)
        return [self.parts[i : i + q] for i in range(0, len(self.parts), q)]

    def as_dict(self):
        return {
            "n_passes": self.n_passes,
            "n_facet_passes": self.n_facet_passes,
            "n_row_slabs": self.n_row_slabs,
            "fold_group": self.fold_group,
            "feed_group": self.feed_group,
            "n_feeds": self.n_feeds,
            "resident_bytes": int(self.resident_bytes),
        }


@dataclass
class SpillPolicy:
    """Where the subgrid stream lives between backward passes."""

    use_spill: bool
    mode: str                 # "none" | "ram" | "disk" | "replay"
    budget_bytes: int
    stream_bytes: int
    spill_dir: str | None = None

    def as_dict(self):
        return {
            "use_spill": self.use_spill,
            "mode": self.mode,
            "budget_bytes": int(self.budget_bytes),
            "stream_bytes": int(self.stream_bytes),
            "disk_backed": self.spill_dir is not None,
        }

    def make_cache(self):
        """A `SpillCache` budgeted per this policy (the fork the cache
        used to price for itself)."""
        from ..utils.spill import SpillCache

        return SpillCache(
            budget_bytes=self.budget_bytes, spill_dir=self.spill_dir,
            policy=self.as_dict(),
        )


@dataclass
class ServePlan:
    """Serve-side shapes + admission pricing for this geometry."""

    max_batch: int
    bucket_sizes: list
    request_bytes: int
    column_bytes: int

    def as_dict(self):
        return {
            "max_batch": self.max_batch,
            "bucket_sizes": list(self.bucket_sizes),
            "request_bytes": int(self.request_bytes),
            "column_bytes": int(self.column_bytes),
        }


@dataclass
class MeshLayout:
    """How the plan shards the streamed pipeline over a device mesh
    (ROADMAP item 1).

    The facet axis is the natural shard — every accumulation is a sum
    over facets, the contraction-over-mesh shape of arXiv 2002.03260 —
    so the layout is 1-D: ``facet_shards`` devices, the facet stack
    zero-padded to ``padded_facets`` (`parallel.mesh.pad_to_shards`;
    padded facets carry zero masks and contribute exact zeros). The
    cost model prices per-shard HBM (``per_shard_stack_bytes`` vs the
    budget → ``fits_hbm``) and the ICI collective traffic (one psum of
    the column's [S, xM, xM] partials per column —
    `utils.profiling.column_collective_bytes`).

    ``status`` records pedigree: ``"stub"`` until an executor consumes
    the layout; the mesh-streamed engine
    (`swiftly_tpu.mesh.MeshStreamedForward` / ``...Backward``) flips it
    to ``"bound"`` and records the padding it actually executed.

    ``collective`` is the PLANNED facet-axis reduction schedule (psum —
    the blocking all-reduce — or ring, the `ppermute` pipeline whose
    chunk rotations hide behind compute; `parallel.sharded`): an
    explicit SWIFTLY_MESH_COLLECTIVE wins, ``auto`` lets CALIBRATED
    coefficients pick the faster-priced row of
    ``collective_candidates`` (`model.price_collective_candidates`) and
    stays psum under defaults — the same defaults-only-RANK rule as the
    colpass candidates. bench asserts executed == planned.
    """

    n_devices: int = 1
    facet_shards: int = 1
    axis: str = "facets"
    status: str = "stub"
    padded_facets: int = 0
    per_shard_stack_bytes: int = 0
    fits_hbm: bool | None = None
    collective_bytes_per_column: int = 0
    collective_bytes_total: int = 0
    collective: str = "psum"
    collective_candidates: list = field(default_factory=list)

    def bind(self):
        """Mark the layout consumed by an executor."""
        self.status = "bound"
        return self

    def as_dict(self):
        out = {
            "n_devices": self.n_devices,
            "facet_shards": self.facet_shards,
            "axis": self.axis,
            "status": self.status,
            "padded_facets": self.padded_facets,
            "per_shard_stack_bytes": int(self.per_shard_stack_bytes),
            "fits_hbm": self.fits_hbm,
            "collective_bytes_per_column": int(
                self.collective_bytes_per_column
            ),
            "collective_bytes_total": int(self.collective_bytes_total),
            "collective": self.collective,
        }
        if self.collective_candidates:
            out["collective_candidates"] = list(
                self.collective_candidates
            )
        return out


def plan_mesh_layout(inputs, mode="roundtrip-streamed", coeffs=None):
    """The mesh layout the cost model chooses for ``inputs``.

    Shard count: every planned device, capped at the facet count (a
    shard holding only zero-padding is exact but pure waste). The HBM
    budget enters as the per-shard residency check: the sharded facet
    stack slice plus a one-column working set must fit the per-device
    budget (``fits_hbm``; None with no budget, e.g. CPU). Collective
    bytes are the forward column psum (ring all-reduce accounting) plus
    — for round-trip modes — the backward's replicated-subgrid
    placement traffic, totalled over the cover.

    Collective schedule: SWIFTLY_MESH_COLLECTIVE=psum|ring forces the
    stage; ``auto`` (default) prices both schedules when ``coeffs`` is
    given and lets a CALIBRATED model pick the cheaper one, otherwise
    keeps psum — defaults only rank, they never flip the executed
    schedule (the same gate the colpass candidates obey).
    """
    from ..parallel.mesh import pad_to_shards, resolve_collective
    from ..utils.profiling import column_collective_bytes

    shards = max(1, min(int(inputs.n_devices), int(inputs.n_facets)))
    padded = pad_to_shards(inputs.n_facets, shards)
    per_facet = inputs.yB * inputs.yB * (
        inputs.dtype_bytes if inputs.real_facets else inputs.per_el
    )
    per_shard = (padded // shards) * per_facet
    fits = None
    if inputs.hbm_budget:
        fits = bool(per_shard + 3e9 <= inputs.hbm_budget)
    core = inputs.base().core
    col_fwd = column_collective_bytes(
        core, shards, inputs.subgrids_per_column, "forward"
    )
    total = col_fwd * inputs.n_columns
    if mode == "roundtrip-streamed":
        total += inputs.n_columns * column_collective_bytes(
            core, shards, inputs.subgrids_per_column, "backward",
            subgrid_size=inputs.xA,
        )
    layout = MeshLayout(
        n_devices=int(inputs.n_devices),
        facet_shards=shards,
        padded_facets=int(padded),
        per_shard_stack_bytes=int(per_shard),
        fits_hbm=fits,
        collective_bytes_per_column=int(col_fwd),
        collective_bytes_total=int(total),
    )
    env = os.environ.get("SWIFTLY_MESH_COLLECTIVE", "auto")
    resolve_collective(shards)  # reject malformed env values loudly
    if coeffs is not None and shards > 1 and total:
        from .model import price_collective_candidates

        layout.collective_candidates = price_collective_candidates(
            inputs, coeffs, mesh=layout, mode=mode
        )
    if shards <= 1:
        layout.collective = "psum"
    elif env in ("psum", "ring"):
        layout.collective = env
    elif (
        coeffs is not None
        and coeffs.calibrated
        and layout.collective_candidates
    ):
        layout.collective = layout.collective_candidates[0]["collective"]
    else:
        layout.collective = "psum"
    return layout


@dataclass
class Plan:
    """One compiled, executable plan plus its self-description."""

    inputs: PlanInputs
    mode: str
    backward: BackwardPlan
    spill: SpillPolicy
    serve: ServePlan
    mesh: MeshLayout
    forward: dict
    predicted: dict
    alternatives: list = field(default_factory=list)
    coeffs_source: str = "default"

    def artifact_block(self, measured_wall_s=None):
        """The ``plan_compiled`` block bench artifacts stamp (validated
        by `obs.validate_plan_artifact`)."""
        block = {
            "schema": PLAN_SCHEMA,
            "inputs_hash": self.inputs.inputs_hash(),
            "config": self.inputs.config_name,
            "mode": self.mode,
            "backward": self.backward.as_dict(),
            "spill": self.spill.as_dict(),
            "serve": self.serve.as_dict(),
            "mesh": self.mesh.as_dict(),
            "forward": dict(self.forward),
            "predicted": dict(self.predicted),
            "coeffs_source": self.coeffs_source,
            "alternatives": list(self.alternatives),
        }
        if measured_wall_s is not None:
            stamp_measured_wall(block, measured_wall_s)
        return block

    def explain(self):
        """Human-readable plan report (scripts/plan_explain.py)."""
        i = self.inputs
        gib = 2.0 ** 30
        lines = [
            f"plan for {i.config_name or 'custom geometry'} "
            f"({self.mode})",
            f"  cover: N={i.N} facets={i.n_facets}x{i.yB} "
            f"columns={i.n_columns} subgrids={i.n_subgrids}x{i.xA}",
            f"  budget: "
            + (
                f"{i.hbm_budget / gib:.2f} GiB/device"
                if i.hbm_budget
                else "unlimited (CPU)"
            )
            + f" x {i.n_devices} device(s)",
            f"  forward: {self.forward}",
            f"  backward: {self.backward.n_passes} pass(es) = "
            f"{self.backward.n_facet_passes} facet subset(s) x "
            f"{self.backward.n_row_slabs} row slab(s), "
            f"fold_group={self.backward.fold_group}, "
            f"resident {self.backward.resident_bytes / gib:.2f} GiB",
            self._explain_feed(),
            f"  spill: {self.spill.mode} "
            f"(stream {self.spill.stream_bytes / gib:.2f} GiB, "
            f"budget {self.spill.budget_bytes / gib:.2f} GiB)",
            f"  serve: buckets {self.serve.bucket_sizes} "
            f"(request {self.serve.request_bytes} B, "
            f"column {self.serve.column_bytes / 1e6:.1f} MB)",
            f"  mesh: {self.mesh.facet_shards} facet shard(s) over "
            f"{self.mesh.n_devices} device(s) [{self.mesh.status}]"
            + (
                f" — {i.n_facets} facets padded to "
                f"{self.mesh.padded_facets}, "
                f"{self.mesh.per_shard_stack_bytes / gib:.2f} GiB "
                f"stack/shard"
                + (
                    ""
                    if self.mesh.fits_hbm is None
                    else (" (fits HBM)" if self.mesh.fits_hbm
                          else " (EXCEEDS HBM)")
                )
                + f", {self.mesh.collective_bytes_total / 1e9:.2f} GB "
                f"ICI collectives/cover ({self.mesh.collective})"
                if self.mesh.facet_shards > 1
                else ""
            ),
            f"  predicted wall: {self.predicted['wall_s']:.1f} s "
            f"({self.coeffs_source} coefficients), HBM peak "
            f"{self.predicted['hbm_peak_bytes'] / gib:.2f} GiB",
        ]
        stages = self.predicted.get("stages") or {}
        for name, st in stages.items():
            lines.append(f"    {name}: {st['wall_s']:.1f} s")
        if self.alternatives:
            lines.append("  rejected alternatives:")
            for alt in self.alternatives:
                if alt.get("chosen"):
                    continue
                if alt.get("schedule"):
                    lines.append(
                        f"    schedule={alt['schedule']}: "
                        f"{alt['n_feeds']} feed(s) of "
                        f"{alt['feed_group']} pass(es), "
                        f"predicted {alt['predicted_wall_s']:.1f} s"
                    )
                    continue
                lines.append(
                    f"    fold_group={alt['fold_group']}: "
                    f"{alt['n_passes']} passes "
                    f"({alt['n_facet_passes']}x{alt['n_row_slabs']}"
                    + (
                        f", {alt['n_feeds']} feed(s)"
                        if "n_feeds" in alt
                        else ""
                    )
                    + f"), predicted {alt['predicted_wall_s']:.1f} s"
                )
        return "\n".join(lines)

    def _explain_feed(self):
        """The feed-once/fold-many schedule line: passes-per-feed, h2d
        bytes the shared feed removes vs per-pass feeding, and whether
        the fold compute is predicted to hide the feed (overlap)."""
        gib = 2.0 ** 30
        bwd = self.backward
        saved = (bwd.n_passes - bwd.n_feeds) * self.inputs.stream_bytes
        line = (
            f"  feed schedule: {bwd.n_feeds} feed(s) x "
            f"{bwd.feed_group} pass(es)/feed "
            f"(h2d saved vs per-pass feeding: {saved / gib:.2f} GiB)"
        )
        stages = self.predicted.get("stages") or {}
        feed = (stages.get("bwd.feed_group") or {}).get("wall_s")
        fold = (stages.get("bwd.sampled_fold") or {}).get("wall_s")
        if feed and fold:
            if fold >= feed:
                line += (
                    f" — overlap: fold compute ({fold:.1f} s) is "
                    f"predicted to hide the feed ({feed:.1f} s)"
                )
            else:
                line += (
                    f" — overlap: feed-bound ({feed:.1f} s feed vs "
                    f"{fold:.1f} s fold)"
                )
        return line


# ---------------------------------------------------------------------------
# Compilation
# ---------------------------------------------------------------------------


def _predict(inputs, parts, fold_group, coeffs, mode, use_spill,
             fwd_min, reserve, mesh=None, feed_group=1):
    """Predicted per-stage walls + totals for one candidate plan.

    With a multi-shard ``mesh`` the prediction prices PER-SHARD HBM
    (facet stack, backward accumulator and row pipeline all shard over
    the facet axis) and adds the ICI collective stage — `mesh.psum` or
    `mesh.ring_step` per the layout's planned ``collective``, priced by
    bytes (`model.price_collective_stage`, overlap-discounted for
    default-pedigree ring rates). Under the
    feed-once/fold-many schedule the HBM peak carries ``feed_group``
    shared pass residencies, and the feed traffic prices once per feed
    (`price_backward`'s ``bwd.feed_group`` stage).
    """
    shards = mesh.facet_shards if mesh is not None else 1
    stages = []
    if mode in ("streamed", "roundtrip-streamed"):
        stages += price_forward(inputs, coeffs)
    if mode == "roundtrip-streamed":
        stages += price_backward(
            inputs, parts, fold_group, coeffs, spill_fed=use_spill,
            feed_group=feed_group,
        )
    if mesh is not None and shards > 1 and mesh.collective_bytes_total:
        from .model import price_collective_stage

        stages.append(
            price_collective_stage(
                coeffs,
                getattr(mesh, "collective", "psum"),
                mesh.collective_bytes_total,
            )
        )
    wall = sum(s.wall_s for s in stages)
    resident = max(
        (i1 - i0)
        * (
            inputs.per_facet_acc_bytes * (r1 - r0) / inputs.yB
            + (2 * fold_group + 2) * inputs.per_facet_row_bytes
        )
        for i0, i1, r0, r1 in parts
    ) if mode == "roundtrip-streamed" else 0
    if mode == "roundtrip-streamed":
        q = min(max(1, feed_group), len(parts))
        peak = q * resident / shards + fwd_min + reserve
    else:
        peak = inputs.facet_stack_bytes / shards + 3e9
    if inputs.hbm_budget:
        peak = min(peak, inputs.hbm_budget)
    return {
        "wall_s": round(wall, 3),
        "hbm_peak_bytes": int(peak),
        "stages": {s.name: s.as_dict() for s in stages},
    }


def _forward_prediction(inputs, coeffs=None):
    """Predicted forward grouping via the CALIBRATED streamed sizers
    (geometry shim; the executors still bind the real choice).

    The colpass entry records the SAME resolution the executor will
    make (`resolve_colpass` with the mode's in-program facet count —
    per-shard for resident, the facet-slab size for grouped), so
    `bench.py --smoke` can assert executed == planned; the candidates
    list is the ranked einsum-vs-pallas pricing
    (`price_colpass_candidates`) with each row's coefficient stage as
    pedigree, and ``colpass_blocks`` surfaces the tile sizes a refit
    learned from pallas history."""
    from ..parallel.streamed import (
        col_group_for_budget,
        facet_stack_bytes,
        grouped_col_group_for_budget,
    )
    from ..utils.flops import resolve_colpass
    from .model import price_colpass_candidates

    base = inputs.base()
    budget = inputs.hbm_budget

    def _with_colpass(pred, facets_in_program):
        pred["colpass"] = resolve_colpass(
            base.core, max(1, facets_in_program)
        )
        if coeffs is not None:
            pred["colpass_candidates"] = price_colpass_candidates(
                inputs, coeffs
            )
            if (
                pred["colpass"] == "pallas"
                and coeffs.colpass_blocks is not None
            ):
                pred["colpass_blocks"] = dict(coeffs.colpass_blocks)
        return pred

    resident_facets = inputs.n_facets // max(1, inputs.n_devices)
    if budget is None:
        return _with_colpass(
            {"mode": "resident", "col_group": inputs.n_columns,
             "facet_group": None},
            resident_facets,
        )
    if facet_stack_bytes(base, inputs.real_facets) + 3e9 <= budget:
        G = col_group_for_budget(
            base, budget, inputs.n_columns, real=inputs.real_facets
        )
        return _with_colpass(
            {"mode": "resident", "col_group": G, "facet_group": None},
            resident_facets,
        )
    Fg = 1
    slab_b = Fg * inputs.yB * inputs.yB * (
        inputs.dtype_bytes if inputs.real_facets else inputs.per_el
    )
    depth = 1 if 2 * slab_b > 0.5 * budget else 2
    G, chunk = max(
        (
            (max(1, (Gc // c) * c if Gc >= c else Gc), c)
            for c in (4, 3, 2, 1)
            for Gc in (
                grouped_col_group_for_budget(
                    base, budget, inputs.n_columns,
                    inputs.subgrids_per_column, inputs.xA,
                    inputs.real_facets, Fg, c, slab_depth=depth,
                    warn=False,
                ),
            )
        ),
        key=lambda t: (t[0], t[1]),
    )
    return _with_colpass(
        {"mode": "grouped", "col_group": G, "facet_group": Fg,
         "chunk": chunk, "slab_depth": depth},
        Fg,
    )


def compile_plan(
    inputs, history=None, coeffs=None, mode="roundtrip-streamed",
    fwd_min=DEFAULT_FWD_MIN_BYTES, reserve=DEFAULT_RESERVE_BYTES,
    n_facet_env=0, n_row_env=0, allow_spill=True,
    spill_budget=None, spill_dir=None, feed_env=0,
):
    """Search the cost model; emit one `Plan`.

    :param inputs: `PlanInputs` (geometry + budget + device count)
    :param history: artifact records (dicts or paths) for
        `autotune.refit` — measured coefficients unlock parameter
        selection by predicted wall; without history the seed
        heuristics' choices are kept (provable equivalence)
    :param coeffs: explicit `CostCoefficients` (overrides history)
    :param n_facet_env / n_row_env: operator pass-grid overrides
        (bench forwards BENCH_BWD_FACET_PASSES / BENCH_BWD_ROW_SLABS)
    :param allow_spill: False forces the replay cost model (BENCH_SPILL=0)
    :param spill_budget / spill_dir: spill-policy overrides; defaults
        are `utils.spill.spill_budget_bytes()` and SWIFTLY_SPILL_DIR
    :param feed_env: operator passes-per-feed override for the
        feed-once/fold-many schedule (bench's BENCH_BWD_FEED_GROUP;
        0 = let `plan_backward_feed` size it from the budget)
    """
    if coeffs is None:
        if history:
            from .autotune import refit

            coeffs = refit(history)
        else:
            coeffs = CostCoefficients()

    def _passes(fold_group):
        return plan_backward_passes(
            inputs.n_facets, inputs.yB, inputs.per_facet_acc_bytes,
            inputs.per_facet_row_bytes, fold_group, inputs.hbm_budget,
            fwd_min=fwd_min, reserve=reserve,
            n_facet_env=n_facet_env, n_row_env=n_row_env,
        )

    # spill policy resolution happens BEFORE the candidate search: a
    # stream too large for the cache budget (and with no disk backing)
    # replays the forward per pass, and that cost difference is exactly
    # what the fold-group ranking must see
    if spill_budget is None:
        from ..utils.spill import spill_budget_bytes

        spill_budget = spill_budget_bytes()
    if spill_dir is None:
        spill_dir = os.environ.get("SWIFTLY_SPILL_DIR") or None

    def _feed(parts, resident):
        return plan_backward_feed(
            parts, resident, inputs.hbm_budget,
            fwd_min=fwd_min, reserve=reserve, feed_env=feed_env,
        )

    def _spill_mode(parts, feed_group=1):
        # the cache exists to serve feeds AFTER the first; a schedule
        # whose single feed serves every pass never re-reads the stream,
        # so recording it would be pure d2h overhead
        n_feeds = -(-len(parts) // max(1, feed_group))
        if not (allow_spill and n_feeds > 1):
            return "none"
        if inputs.stream_bytes <= spill_budget:
            return "ram"
        if spill_dir:
            return "disk"
        return "replay"

    # the mesh layout falls out of the same model (arXiv 2002.03260):
    # chosen before the candidate search so every prediction prices the
    # per-shard HBM and the ICI collective bytes of the SAME layout
    mesh = plan_mesh_layout(inputs, mode=mode, coeffs=coeffs)

    # -- fold-group search (the measured-feedback lever) ---------------------
    candidates = sorted(
        {inputs.fold_group}
        | {
            fg for fg in _FOLD_GROUP_CANDIDATES
            if fg <= max(1, inputs.n_columns)
        }
    )
    alternatives = []
    best = None
    for fg in candidates:
        parts_c, resident_c = _passes(fg)
        feed_c = _feed(parts_c, resident_c)
        use_spill_c = _spill_mode(parts_c, feed_c) in ("ram", "disk")
        pred_c = _predict(inputs, parts_c, fg, coeffs, mode,
                          use_spill_c, fwd_min, reserve, mesh=mesh,
                          feed_group=feed_c)
        alt = {
            "fold_group": fg,
            "n_passes": len(parts_c),
            "n_facet_passes": len({(p[0], p[1]) for p in parts_c}),
            "n_row_slabs": len({(p[2], p[3]) for p in parts_c}),
            "feed_group": feed_c,
            "n_feeds": -(-len(parts_c) // feed_c),
            "predicted_wall_s": pred_c["wall_s"],
            "chosen": False,
        }
        alternatives.append(alt)
        cand = (
            pred_c["wall_s"], fg, parts_c, resident_c, feed_c, pred_c,
            alt,
        )
        if best is None or cand[0] < best[0]:
            best = cand
    if coeffs.calibrated and mode == "roundtrip-streamed":
        (_wall, fold_group, parts, resident, feed_group, predicted,
         chosen_alt) = best
    else:
        # default coefficients: keep the seed heuristic's fold group —
        # equivalence first, the model only ranks
        fold_group = inputs.fold_group
        parts, resident = _passes(fold_group)
        feed_group = _feed(parts, resident)
        predicted = _predict(
            inputs, parts, fold_group, coeffs, mode,
            _spill_mode(parts, feed_group) in ("ram", "disk"),
            fwd_min, reserve, mesh=mesh, feed_group=feed_group,
        )
        chosen_alt = next(
            a for a in alternatives if a["fold_group"] == fold_group
        )
    chosen_alt["chosen"] = True

    # the fused-schedule alternative: the same grid fed once per pass
    # (the pre-feed-once cost model), recorded so plan_explain can show
    # what the shared feed buys
    if mode == "roundtrip-streamed" and len(parts) > 1:
        pred_pp = _predict(
            inputs, parts, fold_group, coeffs, mode,
            _spill_mode(parts, 1) in ("ram", "disk"), fwd_min,
            reserve, mesh=mesh, feed_group=1,
        )
        alternatives.append(
            {
                "schedule": "per_pass_feed",
                "fold_group": fold_group,
                "n_passes": len(parts),
                "n_facet_passes": len({(p[0], p[1]) for p in parts}),
                "n_row_slabs": len({(p[2], p[3]) for p in parts}),
                "feed_group": 1,
                "n_feeds": len(parts),
                "predicted_wall_s": pred_pp["wall_s"],
                "chosen": feed_group == 1,
            }
        )

    # -- spill policy --------------------------------------------------------
    spill_mode = _spill_mode(parts, feed_group)
    use_spill = spill_mode in ("ram", "disk")
    spill = SpillPolicy(
        use_spill=use_spill, mode=spill_mode,
        budget_bytes=int(spill_budget),
        stream_bytes=int(inputs.stream_bytes), spill_dir=spill_dir,
    )

    # -- serve shapes + admission pricing ------------------------------------
    serve = ServePlan(
        max_batch=inputs.max_batch,
        bucket_sizes=bucket_sizes(inputs.max_batch),
        request_bytes=inputs.xA * inputs.xA * inputs.per_el,
        column_bytes=inputs.n_facets * inputs.m * inputs.yN
        * inputs.per_el,
    )

    return Plan(
        inputs=inputs,
        mode=mode,
        backward=BackwardPlan(parts, fold_group, resident, feed_group),
        spill=spill,
        serve=serve,
        mesh=mesh,
        forward=_forward_prediction(inputs, coeffs),
        predicted=predicted,
        alternatives=alternatives,
        coeffs_source=coeffs.source,
    )


# ---------------------------------------------------------------------------
# Incremental (facet-delta) planning
# ---------------------------------------------------------------------------


@dataclass
class DeltaPlan:
    """Incremental-vs-full pricing for a K-of-J facet update.

    ``mode`` is the cheaper choice for the REQUESTED K ("patch" = delta
    stream + cache patch, "full" = re-record); ``break_even_k`` the
    smallest K at which the full recompute wins (J+1 when the patch
    wins at every K — e.g. replay-mode streams where the full path
    pays no re-record IO either way price differently). Every scanned
    K is kept in ``alternatives`` so `scripts/plan_explain.py --delta`
    prints the whole break-even table, and the rejected choice for the
    requested K is among them (``chosen`` flags), matching
    `compile_plan`'s alternative-recording contract.
    """

    changed_facets: int
    n_facets: int
    mode: str  # "patch" | "full"
    predicted_wall_s: float
    full_wall_s: float
    break_even_k: int
    alternatives: list = field(default_factory=list)
    coeffs_source: str = "default"

    def as_dict(self):
        return {
            "changed_facets": int(self.changed_facets),
            "n_facets": int(self.n_facets),
            "mode": self.mode,
            "predicted_wall_s": round(float(self.predicted_wall_s), 4),
            "full_wall_s": round(float(self.full_wall_s), 4),
            "break_even_k": int(self.break_even_k),
            "coeffs_source": self.coeffs_source,
            "alternatives": list(self.alternatives),
        }

    def explain(self):
        """Human-readable break-even table
        (``scripts/plan_explain.py --delta K``)."""
        lines = [
            f"delta plan: {self.changed_facets} of {self.n_facets} "
            f"facet(s) changed -> {self.mode} "
            f"({self.predicted_wall_s:.2f} s vs full "
            f"{self.full_wall_s:.2f} s, {self.coeffs_source} "
            "coefficients)",
            f"  break-even: full recompute wins from K = "
            f"{self.break_even_k}"
            + (
                " (never within this cover)"
                if self.break_even_k > self.n_facets
                else ""
            ),
            "  K  patch_wall_s  full_wall_s  choice",
        ]
        for alt in self.alternatives:
            mark = " *" if alt.get("chosen") else ""
            lines.append(
                f"  {alt['changed_facets']:>2}  "
                f"{alt['patch_wall_s']:>12.3f}  "
                f"{alt['full_wall_s']:>11.3f}  "
                f"{alt['mode']}{mark}"
            )
        return "\n".join(lines)


def plan_delta(inputs, changed_facets, coeffs=None, history=None):
    """Price a K-changed-facet incremental update against the full
    streamed recompute; pick the cheaper (`DeltaPlan`).

    The incremental path prices a forward RESTRICTED to the K delta
    facets (the linearity argument of docs/incremental.md: the
    restricted column pass is exactly the additive correction) plus the
    patch IO — the delta stream's d2h pull and the cached stream's
    read-modify-write. The full path prices the whole-stack forward
    plus the re-record d2h. Both use the same stage coefficients as
    `compile_plan` — with ``history``, `autotune.refit`'s measured
    rates (autotune-refittable break-even).
    """
    if coeffs is None:
        if history:
            from .autotune import refit

            coeffs = refit(history)
        else:
            coeffs = CostCoefficients()
    k = int(changed_facets)
    n = int(inputs.n_facets)
    if not 1 <= k <= n:
        raise ValueError(
            f"changed_facets must be in [1, {n}] (got {k})"
        )
    stream = int(inputs.stream_bytes)

    def patch_wall(kk):
        # fwd restricted to the K deltas, plus the correction stream's
        # d2h+store (every facet touches every column, so the
        # correction spans the full stream — same bytes the full
        # re-record moves), plus the patch's ONLY extra IO: reading
        # the recorded base for the in-place add.
        fwd = sum(
            s.wall_s
            for s in price_forward(inputs.replace(n_facets=kk), coeffs)
        )
        store = coeffs.price("spill.write", bytes_moved=stream).wall_s
        base_read = coeffs.price("spill.read", bytes_moved=stream).wall_s
        return fwd + store + base_read

    full_wall = (
        sum(s.wall_s for s in price_forward(inputs, coeffs))
        + coeffs.price("spill.write", bytes_moved=stream).wall_s
    )
    alternatives = []
    break_even = n + 1
    for kk in range(1, n + 1):
        pw = patch_wall(kk)
        mode_k = "patch" if pw < full_wall else "full"
        if mode_k == "full" and break_even > n:
            break_even = kk
        alternatives.append(
            {
                "changed_facets": kk,
                "patch_wall_s": round(pw, 4),
                "full_wall_s": round(full_wall, 4),
                "mode": mode_k,
                "chosen": kk == k,
            }
        )
    chosen = alternatives[k - 1]
    return DeltaPlan(
        changed_facets=k,
        n_facets=n,
        mode=chosen["mode"],
        predicted_wall_s=(
            chosen["patch_wall_s"]
            if chosen["mode"] == "patch"
            else full_wall
        ),
        full_wall_s=full_wall,
        break_even_k=break_even,
        alternatives=alternatives,
        coeffs_source=coeffs.source,
    )


# ---------------------------------------------------------------------------
# Cache-tier (serve fabric) planning
# ---------------------------------------------------------------------------


@dataclass
class CacheTierPlan:
    """L1 / L2 / recompute pricing for the shared serve cache fabric.

    For a replica fleet over one `cache.SharedStreamTier`, price where
    each request lands: a per-replica hot-row **L1** hit (the
    ``cache.l1`` rate), an **L2** read of the one resident stream (the
    ``spill.read`` rate the spill cache serves at), or a **recompute**
    (one coalesced column pass — what a stale bounce mid-patch falls
    back to). The L1 hit share follows a zipf-over-subgrids popularity
    model at ``zipf_s``; every scanned L1 size is kept in
    ``alternatives`` (``chosen`` flags) so
    ``scripts/plan_explain.py --cache`` prints the break-even table,
    matching `compile_plan`'s alternative-recording contract.

    ``break_even_l1_rows`` is the smallest per-replica L1 at which the
    expected per-request wall sits within 1% of the best scanned size:
    a bigger L1 buys latency the coefficients can no longer measure,
    it only buys HBM.
    """

    replicas: int
    n_subgrids: int
    row_bytes: int
    zipf_s: float
    stale_rate: float
    l1_hit_wall_s: float
    l2_hit_wall_s: float
    recompute_wall_s: float
    l1_rows: int
    break_even_l1_rows: int
    expected_wall_s: float
    alternatives: list = field(default_factory=list)
    coeffs_source: str = "default"

    def as_dict(self):
        return {
            "replicas": int(self.replicas),
            "n_subgrids": int(self.n_subgrids),
            "row_bytes": int(self.row_bytes),
            "zipf_s": float(self.zipf_s),
            "stale_rate": float(self.stale_rate),
            "l1_hit_wall_s": round(float(self.l1_hit_wall_s), 9),
            "l2_hit_wall_s": round(float(self.l2_hit_wall_s), 9),
            "recompute_wall_s": round(float(self.recompute_wall_s), 6),
            "l1_rows": int(self.l1_rows),
            "break_even_l1_rows": int(self.break_even_l1_rows),
            "expected_wall_s": round(float(self.expected_wall_s), 9),
            "coeffs_source": self.coeffs_source,
            "alternatives": list(self.alternatives),
        }

    def explain(self):
        """Human-readable L1-size table
        (``scripts/plan_explain.py --cache``)."""
        lines = [
            f"cache tier plan: {self.replicas} replica(s) over ONE "
            f"resident stream of {self.n_subgrids} rows "
            f"({self.coeffs_source} coefficients)",
            f"  per request: L1 hit {self.l1_hit_wall_s * 1e6:.2f} us"
            f" | L2 read {self.l2_hit_wall_s * 1e6:.2f} us"
            f" | recompute {self.recompute_wall_s * 1e3:.3f} ms"
            f" (one column pass; stale rate {self.stale_rate})",
            f"  popularity: zipf_s={self.zipf_s} over "
            f"{self.n_subgrids} subgrids; row_bytes={self.row_bytes}",
            f"  break-even L1: {self.break_even_l1_rows} rows/replica "
            "(larger L1s are within 1% of the best scanned wall)",
            "  l1_rows  hit_l1  hit_l2  wall_per_req_us  "
            "fleet_l1_bytes  choice",
        ]
        for alt in self.alternatives:
            mark = " *" if alt.get("chosen") else ""
            lines.append(
                f"  {alt['l1_rows']:>7}  "
                f"{alt['hit_l1']:>6.3f}  "
                f"{alt['hit_l2']:>6.3f}  "
                f"{alt['wall_per_req_s'] * 1e6:>15.2f}  "
                f"{alt['fleet_l1_bytes']:>14d}"
                f"{mark}"
            )
        return "\n".join(lines)


def price_cache_tier(inputs, coeffs=None, history=None, *,
                     replicas=3, l1_rows=None, zipf_s=1.1,
                     stale_rate=0.02):
    """Price the serve fabric's cache tiers for one config + replica
    count; returns a `CacheTierPlan`.

    The L2 (the one resident `utils.spill.SpillCache` recording) is
    COMPLETE, so in steady state a request either hits a replica's L1,
    reads the L2, or — at ``stale_rate``, the mid-patch / stale-bounce
    fraction during facet updates — recomputes one coalesced column
    pass. The L1 hit share for a per-replica capacity of ``c`` rows is
    the zipf top-``c`` mass (rendezvous routing makes each replica's
    popular set look like the global one over its column shard).
    Candidate L1 sizes are scanned in powers of two up to the cover;
    with ``l1_rows`` given, that size is the chosen row, otherwise the
    break-even size is. Coefficients refit from artifact ``history``
    exactly like `plan_delta` (autotune-refittable).
    """
    if coeffs is None:
        if history:
            from .autotune import refit

            coeffs = refit(history)
        else:
            coeffs = CostCoefficients()
    n_replicas = int(replicas)
    if n_replicas < 1:
        raise ValueError(f"replicas must be >= 1 (got {replicas})")
    if not 0.0 <= float(stale_rate) < 1.0:
        raise ValueError(
            f"stale_rate must be in [0, 1) (got {stale_rate})"
        )
    n_rows = int(inputs.n_subgrids)
    row_bytes = inputs.xA * inputs.xA * inputs.per_el
    l1_wall = coeffs.price("cache.l1", bytes_moved=row_bytes).wall_s
    l2_wall = coeffs.price("spill.read", bytes_moved=row_bytes).wall_s
    # a miss recomputes ONE coalesced column pass (the serve scheduler's
    # unit of compute); amortizing over co-batched requests is the
    # scheduler's bonus, not the plan's promise
    recompute_wall = (
        sum(s.wall_s for s in price_forward(inputs, coeffs))
        / max(1, inputs.n_columns)
    )

    # zipf top-c mass: H_c(s) / H_n(s)
    weights = [1.0 / (i ** float(zipf_s)) for i in range(1, n_rows + 1)]
    total_mass = sum(weights)
    prefix = []
    acc = 0.0
    for w in weights:
        acc += w
        prefix.append(acc)

    def expected(c):
        hit_l1 = 0.0 if c <= 0 else prefix[min(c, n_rows) - 1] / total_mass
        hit_l1 *= 1.0 - stale_rate
        hit_l2 = 1.0 - stale_rate - hit_l1
        wall = (
            hit_l1 * l1_wall
            + hit_l2 * l2_wall
            + stale_rate * recompute_wall
        )
        return hit_l1, hit_l2, wall

    candidates = [0]
    c = 1
    while c < n_rows:
        candidates.append(c)
        c *= 2
    candidates.append(n_rows)
    if l1_rows is not None and int(l1_rows) not in candidates:
        candidates = sorted(set(candidates) | {int(l1_rows)})

    priced = [(cc, *expected(cc)) for cc in candidates]
    best_wall = min(p[3] for p in priced)
    break_even = next(
        cc for cc, _h1, _h2, wall in priced
        if wall <= best_wall * 1.01
    )
    chosen_rows = break_even if l1_rows is None else int(l1_rows)
    alternatives = []
    chosen_wall = best_wall
    for cc, h1, h2, wall in priced:
        if cc == chosen_rows:
            chosen_wall = wall
        alternatives.append(
            {
                "l1_rows": cc,
                "hit_l1": round(h1, 4),
                "hit_l2": round(h2, 4),
                "wall_per_req_s": round(wall, 9),
                "fleet_l1_bytes": int(cc * row_bytes * n_replicas),
                "chosen": cc == chosen_rows,
            }
        )
    return CacheTierPlan(
        replicas=n_replicas,
        n_subgrids=n_rows,
        row_bytes=int(row_bytes),
        zipf_s=float(zipf_s),
        stale_rate=float(stale_rate),
        l1_hit_wall_s=l1_wall,
        l2_hit_wall_s=l2_wall,
        recompute_wall_s=recompute_wall,
        l1_rows=chosen_rows,
        break_even_l1_rows=break_even,
        expected_wall_s=chosen_wall,
        alternatives=alternatives,
        coeffs_source=coeffs.source,
    )
