"""Measured-feedback autotune: refit the cost model from run history.

Every bench artifact since PR 1 is provenance-stamped (manifest: device
platform/kind, env, config hash) and carries per-stage telemetry —
``telemetry.stages[<name>]`` with ``total_s`` plus the analytic
``flops`` / moved ``bytes`` the obs instrumentation attributed — and,
since PR 5, a ``trace`` block whose self-time attribution partitions
the leg wall. That history is exactly a measured throughput table:

    rate(stage) = sum(flops) / sum(total_s)          (compute stages)
    rate(stage) = sum(bytes) / sum(total_s)          (transfer stages)

`refit(history)` folds the matching records into `CostCoefficients`
with ``source = "measured"``, which is what unlocks parameter selection
in `compiler.compile_plan` — e.g. the backward fold group is then
picked by predicted wall (dispatch count vs fold-pipeline residency vs
spill re-reads) instead of the static default. Records from a
different platform than requested are skipped, not averaged: a CPU
smoke artifact must never calibrate a TPU plan.
"""

from __future__ import annotations

import glob
import json
import logging

from .model import CostCoefficients

__all__ = ["load_history", "refit"]

logger = logging.getLogger(__name__)


def load_history(patterns):
    """BENCH records from artifact files (JSON record/list/JSONL or the
    round-ledger ``{"parsed": ...}`` shape), for `refit`.

    :param patterns: path/glob strings (or one string)
    """
    if isinstance(patterns, (str, bytes)):
        patterns = [patterns]
    records = []
    for pattern in patterns:
        for path in sorted(glob.glob(str(pattern))):
            try:
                text = open(path).read()
            except OSError as exc:
                logger.warning("history: cannot read %s: %s", path, exc)
                continue
            try:
                data = json.loads(text)
            except json.JSONDecodeError:
                try:
                    data = [
                        json.loads(line)
                        for line in text.splitlines()
                        if line.strip()
                    ]
                except json.JSONDecodeError:
                    logger.warning("history: %s is not JSON/JSONL", path)
                    continue
            if isinstance(data, dict) and "parsed" in data:
                data = data["parsed"]
            if isinstance(data, dict):
                data = [data]
            records.extend(r for r in data if isinstance(r, dict))
    return records


def _record_platform(record):
    manifest = record.get("manifest") or {}
    return (manifest.get("device") or {}).get("platform")


def refit(history, platform=None, dispatch_s=None):
    """Fit per-stage throughput coefficients from artifact history.

    :param history: records (dicts) or paths/globs (`load_history`)
    :param platform: only fold in records stamped for this platform
        (default: the first record's platform — mixing a CPU smoke into
        a TPU fit would poison every rate)
    :param dispatch_s: override the per-dispatch latency floor (not
        derivable from stage telemetry; measured ~0.1 s on the tunnel
        runtime, scripts/roofline.py)
    :return: `CostCoefficients` with ``source="measured"`` when at
        least one stage was fit, else the defaults (``"default"``)
    """
    if history and all(
        isinstance(h, (str, bytes)) for h in (
            history if isinstance(history, (list, tuple)) else [history]
        )
    ):
        history = load_history(history)
    elif isinstance(history, dict):
        history = [history]
    history = [r for r in (history or []) if isinstance(r, dict)]
    if platform is None:
        for rec in history:
            platform = _record_platform(rec)
            if platform:
                break
    flops_acc = {}   # stage -> [flops, seconds]
    bytes_acc = {}   # stage -> [bytes, seconds]
    n_used = 0
    best_blocks = None  # fastest recorded pallas column-pass tile set
    best_block_rate = 0.0
    for rec in history:
        plat = _record_platform(rec)
        if platform and plat and plat != platform:
            continue
        stages = (rec.get("telemetry") or {}).get("stages") or {}
        # learn Pallas column-pass block sizes: of the records that ran
        # colpass=pallas AND stamped their tiles, keep the tile set of
        # the record with the best measured column-stage rate — this is
        # what replaces the hardcoded SWIFTLY_COLPASS_SBLOCK=256 /
        # bm=bn=bk=256 defaults once real history exists
        plan = rec.get("plan") or {}
        blocks = plan.get("colpass_blocks")
        if plan.get("colpass") == "pallas" and isinstance(blocks, dict):
            for stage_name in ("fwd.column_pass.pallas", "fwd.slab_step"):
                entry = stages.get(stage_name) or {}
                total_s = entry.get("total_s") or 0.0
                if entry.get("flops") and total_s > 0:
                    rate = entry["flops"] / total_s
                    if rate > best_block_rate:
                        best_block_rate = rate
                        best_blocks = dict(blocks)
                    break
        used = False
        for name, entry in stages.items():
            total_s = entry.get("total_s") or 0.0
            if total_s <= 0:
                continue
            if entry.get("flops"):
                acc = flops_acc.setdefault(name, [0.0, 0.0])
                acc[0] += entry["flops"]
                acc[1] += total_s
                used = True
            if entry.get("bytes"):
                acc = bytes_acc.setdefault(name, [0.0, 0.0])
                acc[0] += entry["bytes"]
                acc[1] += total_s
                used = True
        # PR-5 trace self-time blocks refine stages the registry missed
        # (a stage with self-time but no flops attribution still tells
        # us nothing about throughput, so only flops/bytes stages fit)
        if used:
            n_used += 1
    if not n_used:
        return CostCoefficients()
    coeffs = CostCoefficients(
        flops_per_s={
            name: acc[0] / acc[1] for name, acc in flops_acc.items()
        },
        bytes_per_s={
            name: acc[0] / acc[1] for name, acc in bytes_acc.items()
        },
        source="measured",
        n_records=n_used,
        platform=platform,
        colpass_blocks=best_blocks,
    )
    if dispatch_s is not None:
        coeffs.dispatch_s = float(dispatch_s)
    return coeffs
