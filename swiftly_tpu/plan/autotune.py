"""Measured-feedback autotune: refit the cost model from run history.

Every bench artifact since PR 1 is provenance-stamped (manifest: device
platform/kind, env, config hash) and carries per-stage telemetry —
``telemetry.stages[<name>]`` with ``total_s`` plus the analytic
``flops`` / moved ``bytes`` the obs instrumentation attributed — and,
since PR 5, a ``trace`` block whose self-time attribution partitions
the leg wall. That history is exactly a measured throughput table:

    rate(stage) = sum(flops) / sum(total_s)          (compute stages)
    rate(stage) = sum(bytes) / sum(total_s)          (transfer stages)

`refit(history)` folds the matching records into `CostCoefficients`
with ``source = "measured"``, which is what unlocks parameter selection
in `compiler.compile_plan` — e.g. the backward fold group is then
picked by predicted wall (dispatch count vs fold-pipeline residency vs
spill re-reads) instead of the static default. Records from a
different platform than requested are skipped, not averaged: a CPU
smoke artifact must never calibrate a TPU plan.
"""

from __future__ import annotations

import glob
import json
import logging
import math

from .model import CostCoefficients

__all__ = [
    "ledger_readiness",
    "load_history",
    "refit",
    "refit_from_ledger",
]

logger = logging.getLogger(__name__)


def load_history(patterns):
    """BENCH records from artifact files (JSON record/list/JSONL or the
    round-ledger ``{"parsed": ...}`` shape), for `refit`.

    :param patterns: path/glob strings (or one string)
    """
    if isinstance(patterns, (str, bytes)):
        patterns = [patterns]
    records = []
    for pattern in patterns:
        for path in sorted(glob.glob(str(pattern))):
            try:
                text = open(path).read()
            except OSError as exc:
                logger.warning("history: cannot read %s: %s", path, exc)
                continue
            try:
                data = json.loads(text)
            except json.JSONDecodeError:
                try:
                    data = [
                        json.loads(line)
                        for line in text.splitlines()
                        if line.strip()
                    ]
                except json.JSONDecodeError:
                    logger.warning("history: %s is not JSON/JSONL", path)
                    continue
            if isinstance(data, dict) and "parsed" in data:
                data = data["parsed"]
            if isinstance(data, dict):
                data = [data]
            records.extend(r for r in data if isinstance(r, dict))
    return records


def _record_platform(record):
    manifest = record.get("manifest") or {}
    return (manifest.get("device") or {}).get("platform")


def refit(history, platform=None, dispatch_s=None):
    """Fit per-stage throughput coefficients from artifact history.

    :param history: records (dicts) or paths/globs (`load_history`)
    :param platform: only fold in records stamped for this platform
        (default: the first record's platform — mixing a CPU smoke into
        a TPU fit would poison every rate)
    :param dispatch_s: override the per-dispatch latency floor (not
        derivable from stage telemetry; measured ~0.1 s on the tunnel
        runtime, scripts/roofline.py)
    :return: `CostCoefficients` with ``source="measured"`` when at
        least one stage was fit, else the defaults (``"default"``)
    """
    if history and all(
        isinstance(h, (str, bytes)) for h in (
            history if isinstance(history, (list, tuple)) else [history]
        )
    ):
        history = load_history(history)
    elif isinstance(history, dict):
        history = [history]
    history = [r for r in (history or []) if isinstance(r, dict)]
    if platform is None:
        for rec in history:
            platform = _record_platform(rec)
            if platform:
                break
    flops_acc = {}   # stage -> [flops, seconds]
    bytes_acc = {}   # stage -> [bytes, seconds]
    n_used = 0
    best_blocks = None  # fastest recorded pallas column-pass tile set
    best_block_rate = 0.0
    for rec in history:
        plat = _record_platform(rec)
        if platform and plat and plat != platform:
            continue
        stages = (rec.get("telemetry") or {}).get("stages") or {}
        # learn Pallas column-pass block sizes: of the records that ran
        # colpass=pallas AND stamped their tiles, keep the tile set of
        # the record with the best measured column-stage rate — this is
        # what replaces the hardcoded SWIFTLY_COLPASS_SBLOCK=256 /
        # bm=bn=bk=256 defaults once real history exists
        plan = rec.get("plan") or {}
        blocks = plan.get("colpass_blocks")
        if plan.get("colpass") == "pallas" and isinstance(blocks, dict):
            for stage_name in ("fwd.column_pass.pallas", "fwd.slab_step"):
                entry = stages.get(stage_name) or {}
                total_s = entry.get("total_s") or 0.0
                if entry.get("flops") and total_s > 0:
                    rate = entry["flops"] / total_s
                    if rate > best_block_rate:
                        best_block_rate = rate
                        best_blocks = dict(blocks)
                    break
        used = False
        for name, entry in stages.items():
            total_s = entry.get("total_s") or 0.0
            if total_s <= 0:
                continue
            if entry.get("flops"):
                acc = flops_acc.setdefault(name, [0.0, 0.0])
                acc[0] += entry["flops"]
                acc[1] += total_s
                used = True
            if entry.get("bytes"):
                acc = bytes_acc.setdefault(name, [0.0, 0.0])
                acc[0] += entry["bytes"]
                acc[1] += total_s
                used = True
        # PR-5 trace self-time blocks refine stages the registry missed
        # (a stage with self-time but no flops attribution still tells
        # us nothing about throughput, so only flops/bytes stages fit)
        if used:
            n_used += 1
    if not n_used:
        return CostCoefficients()
    coeffs = CostCoefficients(
        flops_per_s={
            name: acc[0] / acc[1] for name, acc in flops_acc.items()
        },
        bytes_per_s={
            name: acc[0] / acc[1] for name, acc in bytes_acc.items()
        },
        source="measured",
        n_records=n_used,
        platform=platform,
        colpass_blocks=best_blocks,
    )
    if dispatch_s is not None:
        coeffs.dispatch_s = float(dispatch_s)
    return coeffs


# ---------------------------------------------------------------------------
# Ledger-driven refit (obs.ledger plan_accuracy history)
# ---------------------------------------------------------------------------


def _ledger_entries(history):
    """``plan_accuracy`` blocks from mixed input: blocks, full BENCH
    records carrying one, or paths/globs (`load_history` shapes,
    including the ledger's own JSONL)."""
    from ..obs.ledger import PLAN_ACCURACY_SCHEMA

    if history and all(
        isinstance(h, (str, bytes)) for h in (
            history if isinstance(history, (list, tuple)) else [history]
        )
    ):
        history = load_history(history)
    elif isinstance(history, dict):
        history = [history]
    entries = []
    for rec in history or []:
        if not isinstance(rec, dict):
            continue
        block = rec
        if isinstance(rec.get("plan_accuracy"), dict):
            block = rec["plan_accuracy"]
        if block.get("schema") == PLAN_ACCURACY_SCHEMA:
            entries.append(block)
    return entries


def _ledger_stage_stats(entries):
    """Per-stage fit accumulators over ledger entries.

    Each covered stage contributes one throughput sample per entry:
    ``flops / measured_wall_s`` when the plan attributed FLOPs, else
    ``bytes / measured_wall_s`` (a stage priced by both would
    double-count one wall — prefer the compute rate, like `refit`'s
    pricing the other way around). Returns
    ``{stage: {"kind", "n", "sum_units", "sum_s", "rates"}}``.
    """
    stats = {}
    for entry in entries:
        for name, stage in (entry.get("stages") or {}).items():
            if not isinstance(stage, dict):
                continue
            meas = stage.get("measured_wall_s")
            if not isinstance(meas, (int, float)) or meas <= 0:
                continue
            if stage.get("flops"):
                kind, units = "flops", float(stage["flops"])
            elif stage.get("bytes"):
                kind, units = "bytes", float(stage["bytes"])
            else:
                continue
            acc = stats.setdefault(
                name,
                {"kind": kind, "n": 0, "sum_units": 0.0, "sum_s": 0.0,
                 "rates": []},
            )
            if acc["kind"] != kind:
                continue  # mixed attribution across entries: keep first
            acc["n"] += 1
            acc["sum_units"] += units
            acc["sum_s"] += float(meas)
            acc["rates"].append(units / float(meas))
    return stats


def ledger_readiness(history, platform=None, min_samples=2,
                     max_rel_spread=0.5):
    """Is the accumulated calibration history good enough to refit?

    Three gates per stage, all from the ledger alone: enough samples
    (``min_samples``), the right platform (entries from another
    platform are skipped, not averaged — same rule as `refit`), and
    low variance (relative std of the per-entry throughput samples at
    most ``max_rel_spread`` — a stage whose measured rate swings 2x
    between runs would fit a coefficient that misprices every run).

    :return: ``{"ready", "platform", "n_records", "stages": {name:
        {"kind", "n", "rate", "rel_spread", "ready"}}, "reasons"}`` —
        ``ready`` is True when at least one stage passes every gate
    """
    entries = _ledger_entries(history)
    if platform is None:
        for entry in entries:
            if entry.get("platform"):
                platform = entry["platform"]
                break
    matched = [
        e for e in entries
        if not (platform and e.get("platform")
                and e.get("platform") != platform)
    ]
    stats = _ledger_stage_stats(matched)
    stages = {}
    for name in sorted(stats):
        acc = stats[name]
        rates = acc["rates"]
        mean = sum(rates) / len(rates)
        rel = None
        if len(rates) > 1 and mean > 0:
            var = sum((r - mean) ** 2 for r in rates) / len(rates)
            rel = math.sqrt(var) / mean
        ok = (
            acc["n"] >= int(min_samples)
            and rel is not None and rel <= float(max_rel_spread)
            and acc["sum_s"] > 0
        )
        stages[name] = {
            "kind": acc["kind"],
            "n": acc["n"],
            "rate": acc["sum_units"] / acc["sum_s"],
            "rel_spread": None if rel is None else round(rel, 4),
            "ready": ok,
        }
    ready = any(s["ready"] for s in stages.values())
    reasons = []
    if not entries:
        reasons.append("no plan_accuracy entries in history")
    elif not matched:
        reasons.append(f"no entries for platform {platform!r}")
    elif not stats:
        reasons.append("no covered stages with flops/bytes attribution")
    elif not ready:
        reasons.append(
            f"no stage has >= {min_samples} samples with relative "
            f"spread <= {max_rel_spread}"
        )
    return {
        "ready": ready,
        "platform": platform,
        "n_records": len(matched),
        "min_samples": int(min_samples),
        "max_rel_spread": float(max_rel_spread),
        "stages": stages,
        "reasons": reasons,
    }


def refit_from_ledger(history, platform=None, min_samples=2,
                      max_rel_spread=0.5, dispatch_s=None):
    """Fit coefficients from accumulated ``plan_accuracy`` history.

    The ledger-driven twin of `refit`: instead of raw telemetry this
    reads the reconciled per-stage records the ledger stamped
    (`obs.ledger.plan_accuracy_block` / the JSONL calibration history),
    so ONLY stages that passed the `ledger_readiness` gates are fit —
    ``rate = Σ units / Σ measured_wall_s`` over the matched entries.
    The result carries ``source="ledger"`` provenance, which the plan
    compiler accepts as calibrated exactly like ``"measured"``
    (`CostCoefficients.calibrated`): the first real TPU session refits
    itself from artifacts instead of hand-curated runs.

    :param history: ``plan_accuracy`` blocks, records carrying one, or
        paths/globs of the JSONL calibration history
    :return: `CostCoefficients` with ``source="ledger"`` when at least
        one stage was ready, else the defaults (``"default"``)
    """
    readiness = ledger_readiness(
        history, platform=platform, min_samples=min_samples,
        max_rel_spread=max_rel_spread,
    )
    if not readiness["ready"]:
        logger.info(
            "ledger refit not ready: %s", "; ".join(readiness["reasons"])
        )
        return CostCoefficients()
    flops_per_s = {}
    bytes_per_s = {}
    for name, stage in readiness["stages"].items():
        if not stage["ready"]:
            continue
        target = flops_per_s if stage["kind"] == "flops" else bytes_per_s
        target[name] = stage["rate"]
    coeffs = CostCoefficients(
        flops_per_s=flops_per_s,
        bytes_per_s=bytes_per_s,
        source="ledger",
        n_records=readiness["n_records"],
        platform=readiness["platform"],
    )
    if dispatch_s is not None:
        coeffs.dispatch_s = float(dispatch_s)
    return coeffs
