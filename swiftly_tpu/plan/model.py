"""Declarative cost model: one pricing of the streamed pipeline's stages.

Until this package existed, the same geometry was priced four ways —
`bench._plan_backward_passes` + the bench HBM sizers, the
`serve.scheduler` power-of-two buckets, `utils.spill.SpillCache`
budgeting, and the serve admission byte projections — each with its own
copy of the arithmetic (ROADMAP item 4). This module is the single
model those consumers now share: it takes ``(N, facet/subgrid geometry,
dtype, HBM budget, device count)`` as a `PlanInputs` and prices every
stage (facet prep, column groups, sampled fold, spill traffic, d2h/h2d,
serve batch shapes) as bytes + FLOPs + an estimated wall built from
`CostCoefficients` — static defaults, or per-stage throughputs refit
from measured artifact history by `plan.autotune`.

The FLOP formulas are NOT re-derived here: every stage count delegates
to `utils.flops` (the same functions the obs instrumentation attributes
with), so the model can never silently diverge from what the executors
report. Likewise the forward group sizing reuses the calibrated
`parallel.streamed` sizers through a geometry shim (`PlanInputs.base()`)
instead of forking their transient accounting. DaggerFFT
(arXiv 2601.12209) is the task-graph/cost-model framing; "Large-Scale
DFT on TPUs" (arXiv 2002.03260) is why the mesh layout must fall out of
the same model rather than a separate heuristic (see
`compiler.MeshLayout`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace

import numpy as np

__all__ = [
    "CostCoefficients",
    "DEFAULT_FWD_MIN_BYTES",
    "DEFAULT_RESERVE_BYTES",
    "PlanInputs",
    "StageCost",
    "bucket_shape",
    "bucket_sizes",
    "hbm_budget_bytes",
    "price_collective_candidates",
    "price_collective_stage",
    "price_colpass_candidates",
    "projected_column_bytes",
    "projected_request_bytes",
]

# The backward planner's residency constants (what the forward's
# auto-sizers must be left, plus fold row-blocks + donation-copy slack).
# Measured on the 32k roundtrip (see bench.py r2 notes); ONE definition
# here, consumed by bench and the compiler alike.
DEFAULT_FWD_MIN_BYTES = 3.3e9
DEFAULT_RESERVE_BYTES = 1.2e9


def hbm_budget_bytes(headroom=0.0, device=None, default=None,
                     honor_env_on_cpu=True):
    """Per-device HBM budget in bytes — THE parser of SWIFTLY_HBM_BUDGET.

    ``None`` means unlimited (CPU / unknown device with no ``default``).
    Every call site that used to read the env var itself (bench.py's
    backward sizing, `parallel.streamed._hbm_budget`) now delegates
    here, so the env contract cannot fork again.

    :param headroom: caller-held resident bytes subtracted from the
        budget (e.g. `StreamedForward.hbm_headroom`)
    :param default: fallback bytes when the probe finds nothing on an
        accelerator (the streamed executors pass their historical 14e9;
        bench passes None — "unpartitioned")
    :param honor_env_on_cpu: bench semantics (True) apply an explicit
        SWIFTLY_HBM_BUDGET even on CPU — useful to exercise partitioned
        plans in CPU tests; the streamed executors (False) stay
        unlimited on CPU regardless, their historical behaviour.
    """
    env = os.environ.get("SWIFTLY_HBM_BUDGET")
    if env and honor_env_on_cpu:
        return float(env) - headroom
    try:
        import jax

        dev = device if device is not None else jax.devices()[0]
        platform = dev.platform
    except Exception:  # pragma: no cover - jax unavailable/uninitialised
        dev, platform = None, None
    if platform == "cpu":
        return None
    if env:
        return float(env) - headroom
    from ..utils.profiling import probe_hbm_bytes

    limit = probe_hbm_bytes(dev) if platform else None
    if limit is None:
        limit = default
    if limit is None:
        return None
    return limit - headroom


# ---------------------------------------------------------------------------
# Geometry
# ---------------------------------------------------------------------------


class _GeomCore:
    """The geometry surface of a backend core, detached from any backend
    state — just enough for `utils.flops` and the `parallel.streamed`
    sizers to price a plan without building facet data or touching a
    device."""

    def __init__(self, N, yN, xM, dtype_bytes, planar):
        self.N = int(N)
        self.yN_size = int(yN)
        self.xM_size = int(xM)
        self.xM_yN_size = int(xM) * int(yN) // int(N)
        self.backend = "planar" if planar else "jax"
        self.dtype = np.dtype(
            {4: np.float32, 8: np.float64}[int(dtype_bytes)]
            if planar
            else {4: np.complex64, 8: np.complex128}.get(
                int(dtype_bytes) // 2, np.complex64
            )
        )


class _GeomStack:
    def __init__(self, size, n):
        self.size = int(size)
        self.n_real = self.n_total = int(n)

    def __len__(self):
        return self.n_total


class _GeomConfig:
    def __init__(self, xA):
        self.max_subgrid_size = int(xA)


class _GeomBase:
    """Duck-typed `_StreamedBase` for the calibrated streamed sizers."""

    def __init__(self, core, stack, config):
        self.core = core
        self.stack = stack
        self.config = config
        self.mesh = None


@dataclass(frozen=True)
class PlanInputs:
    """Everything the plan compiler needs to price one cover.

    Geometry is the COVER's, not just the catalogue row's, so sparse /
    partial covers price what they actually run (`from_cover`).
    """

    N: int
    yB: int                      # padded facet size
    yN: int
    xA: int                      # subgrid size
    xM: int
    n_facets: int
    n_columns: int               # distinct subgrid column offsets
    subgrids_per_column: int
    dtype_bytes: int = 4
    planar: bool = True
    real_facets: bool = False
    hbm_budget: float | None = None   # per-device bytes; None = unlimited
    n_devices: int = 1
    fold_group: int = 2
    max_batch: int = 64               # serve coalescing cap
    config_name: str | None = None

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_config(cls, name, **overrides):
        """Inputs for a full cover of one catalogue config."""
        from ..models import SWIFT_CONFIGS

        params = SWIFT_CONFIGS[name]
        N, yB = params["N"], params["yB_size"]
        xA = params["xA_size"]
        n_side = -(-N // xA)
        return cls(
            N=N, yB=yB, yN=params["yN_size"], xA=xA,
            xM=params["xM_size"],
            n_facets=(-(-N // yB)) ** 2,
            n_columns=n_side, subgrids_per_column=n_side,
            config_name=name,
            **overrides,
        )

    @classmethod
    def from_cover(cls, config, facet_configs, subgrid_configs,
                   **overrides):
        """Inputs priced from an ACTUAL cover (sparse/partial included)."""
        core = config.core
        n_cols = len({sg.off0 for sg in subgrid_configs})
        planar = core.backend == "planar"
        return cls(
            N=config.image_size, yB=facet_configs[0].size,
            yN=core.yN_size, xA=subgrid_configs[0].size,
            xM=core.xM_size,
            n_facets=len(facet_configs), n_columns=n_cols,
            subgrids_per_column=len(subgrid_configs) // n_cols,
            dtype_bytes=np.dtype(core.dtype).itemsize,
            planar=planar,
            **overrides,
        )

    def replace(self, **kw):
        return replace(self, **kw)

    # -- derived geometry ------------------------------------------------------

    @property
    def m(self):
        """Contribution rows per column (xM * yN / N)."""
        return self.xM * self.yN // self.N

    @property
    def per_el(self):
        """Bytes per grid element (planar keeps (re, im) planes)."""
        return self.dtype_bytes * (2 if self.planar else 1)

    @property
    def n_subgrids(self):
        return self.n_columns * self.subgrids_per_column

    @property
    def per_facet_acc_bytes(self):
        """One facet's whole [yB, yB] image accumulator."""
        return self.yB * self.yB * self.per_el

    @property
    def per_facet_row_bytes(self):
        """One facet's [m, yB] column-rows buffer."""
        return self.m * self.yB * self.per_el

    @property
    def stream_bytes(self):
        """The whole subgrid stream (what one spill fill persists)."""
        return self.n_subgrids * self.xA * self.xA * self.per_el

    @property
    def facet_stack_bytes(self):
        per = self.dtype_bytes if self.real_facets else self.per_el
        return self.n_facets * self.yB * self.yB * per

    def base(self):
        """Geometry shim the `parallel.streamed` sizers accept."""
        return _GeomBase(
            _GeomCore(self.N, self.yN, self.xM, self.dtype_bytes,
                      self.planar),
            _GeomStack(self.yB, self.n_facets),
            _GeomConfig(self.xA),
        )

    def inputs_hash(self):
        """Deterministic short hash of the pricing inputs (stamped into
        artifacts so two plans are comparable iff their hashes match)."""
        from ..obs.manifest import config_hash
        from dataclasses import asdict

        return config_hash(asdict(self))


# ---------------------------------------------------------------------------
# Serve batch shapes + admission byte projections
# ---------------------------------------------------------------------------


def bucket_shape(n):
    """Next power of two >= n — the serve compile-shape bucket (one
    definition; `serve.scheduler` delegates here)."""
    b = 1
    while b < n:
        b *= 2
    return b


def bucket_sizes(max_batch):
    """The distinct dispatch shapes bucket padding can produce under a
    ``max_batch`` cap: 1 (the single-request program) and every power
    of two up to the cap, with the cap itself as the largest shape."""
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(int(max_batch))
    return sizes


def _per_element_bytes(core):
    return np.dtype(core.dtype).itemsize * (
        2 if core.backend == "planar" else 1
    )


def projected_request_bytes(config):
    """Projected HBM bytes of one finished subgrid — the admission
    queue's per-request cost (moved here from `serve.service`; the
    service and `serve.fleet` both price from this one definition)."""
    return config.max_subgrid_size ** 2 * _per_element_bytes(config.core)


def projected_column_bytes(fwd):
    """Projected HBM bytes of one pending column's intermediates — the
    [F, m, yN] ``extract_columns_batch`` product the coalescing batcher
    materialises once per column program."""
    core = fwd.core
    return (
        len(fwd.stack) * core.xM_yN_size * core.yN_size
        * _per_element_bytes(core)
    )


# ---------------------------------------------------------------------------
# Stage pricing
# ---------------------------------------------------------------------------


@dataclass
class StageCost:
    """One stage's priced cost: FLOPs and/or bytes plus the wall the
    coefficients predict for it."""

    name: str
    flops: int = 0
    bytes_moved: int = 0
    dispatches: int = 0
    wall_s: float = 0.0

    def as_dict(self):
        out = {"wall_s": round(self.wall_s, 4)}
        if self.flops:
            out["flops"] = int(self.flops)
        if self.bytes_moved:
            out["bytes"] = int(self.bytes_moved)
        if self.dispatches:
            out["dispatches"] = int(self.dispatches)
        return out


# Default per-stage-family effective throughputs. DELIBERATELY coarse:
# they rank alternatives and give an order-of-magnitude wall; artifact
# blocks stamp ``coeffs_source: "default"`` so nothing downstream
# (bench_compare's mispricing flag) treats an uncalibrated prediction
# as a measured contract. The v5e-derived anchors: forward streams at
# ~26% of the 65.7 TF/s f32-HIGHEST peak, the backward fold measured
# 13.7% (docs/performance.md), tunnel dispatch latency ~0.1 s/chain
# (scripts/roofline.py).
_DEFAULT_FLOPS_PER_S = {
    "fwd": 17e12,
    # the fused Pallas column pass targets >=30% of the 65.7 TF/s
    # f32-HIGHEST v5e peak (vs 18.1% measured for the einsum chain,
    # roofline_32k.jsonl) — coarse anchor until autotune refits the
    # exact stage name from a recorded pallas run
    "fwd.column_pass.pallas": 22e12,
    "bwd.column_pass": 9e12,
    "bwd.column_pass.pallas": 12e12,
    "bwd.sampled_fold": 9e12,
    "bwd": 9e12,
    # visibility degrid/grid: gather/scatter plus a [B, W, W]
    # contraction — VPU work with data-dependent addressing, nowhere
    # near MXU rates. Coarse anchors that RANK bucket candidates in
    # `plan.vis.price_vis`; the stages record attributed flops under
    # the same names, so `plan.autotune.refit` supersedes them from
    # the first recorded `bench.py --vis` artifact
    "vis.degrid": 2e12,
    "vis.grid": 1e12,
}
_DEFAULT_BYTES_PER_S = {
    "spill.h2d": 6e9,
    "spill.write": 3e9,
    "spill.read": 6e9,
    # hot-row L1 hits in the serve cache fabric: an in-process dict
    # probe plus one row memcpy-equivalent — far above the spill L2's
    # read path, which a miss falls through to (`plan.price_cache_tier`
    # ranks L1 size against it)
    "cache.l1": 20e9,
    # the feed-once/fold-many stage: wall BLOCKED on the shared feed
    # (cache read + h2d dispatch, after the async prefetch and the fold
    # overlap hide what they can) per cache-fed byte. Defaults to the
    # wire rate; the measured stage the executor records under the same
    # name refits it to the post-overlap effective rate
    "bwd.feed_group": 6e9,
    # per-link ICI ring bandwidth anchor (v5e ~45 GB/s effective);
    # coarse like every default — it ranks mesh plans, it is not a
    # contract (measured coefficients refit it like any other stage)
    "mesh.psum": 45e9,
    # the ppermute ring moves the same wire bytes over the same links
    # (XLA's all-reduce on a 1-D mesh IS a ring) — the ring schedule's
    # win is overlap, modelled as RING_OVERLAP_DISCOUNT below, not a
    # faster default rate. A measured mesh.ring_step coefficient (the
    # engine's stage timer records EXPOSED wall, overlap already
    # subtracted) replaces both the rate and the discount.
    "mesh.ring_step": 45e9,
}
_DEFAULT_DISPATCH_S = 0.1

# Fraction of the ring collective's raw wire wall hidden behind the next
# facet block's shard-local contraction and the next group's h2d staging
# fill (the engine stores one group BEHIND compute and the triple-buffer
# prefetch thread fills staging concurrently — mesh/engine._spill_store).
# A coarse default-pedigree anchor like the rates above: it RANKS the
# ring against the blocking psum; a refit mesh.ring_step rate (measured
# exposed wall) supersedes it (`price_collective_candidates` then prices
# with zero additional discount).
RING_OVERLAP_DISCOUNT = 0.6


@dataclass
class CostCoefficients:
    """Per-stage throughput coefficients the wall model divides by.

    ``source`` records pedigree: ``"default"`` (static anchors above),
    ``"measured"`` (refit from raw artifact telemetry by
    `plan.autotune.refit`) or ``"ledger"`` (fit from the accumulated
    ``plan_accuracy`` calibration history by
    `plan.autotune.refit_from_ledger`). The compiler only lets
    CALIBRATED coefficients (`calibrated` — measured or ledger) change
    plan parameters; defaults rank alternatives but the seed heuristics
    keep the choice, so seed-geometry plans stay provably equivalent to
    the pre-plan forks.
    """

    flops_per_s: dict = field(default_factory=dict)
    bytes_per_s: dict = field(default_factory=dict)
    dispatch_s: float = _DEFAULT_DISPATCH_S
    source: str = "default"
    n_records: int = 0
    platform: str | None = None
    # measured-best Pallas column-pass tile sizes from artifact history
    # ({"bm", "bn", "bk", "sblock"}, `plan.autotune.refit`) — None until
    # a recorded pallas run exists; surfaced by `scripts/plan_explain.py
    # --colpass` for export as SWIFTLY_COLPASS_BM/BN/BK/SBLOCK
    colpass_blocks: dict | None = None

    @property
    def calibrated(self):
        """Measurement-backed pedigree — what unlocks plan parameter
        selection in `compiler.compile_plan`."""
        return self.source in ("measured", "ledger")

    def flops_rate(self, stage):
        for key in (stage, stage.split(".")[0]):
            if key in self.flops_per_s:
                return self.flops_per_s[key]
            if key in _DEFAULT_FLOPS_PER_S:
                return _DEFAULT_FLOPS_PER_S[key]
        return _DEFAULT_FLOPS_PER_S["bwd"]

    def bytes_rate(self, stage):
        for key in (stage, stage.split(".")[0]):
            if key in self.bytes_per_s:
                return self.bytes_per_s[key]
            if key in _DEFAULT_BYTES_PER_S:
                return _DEFAULT_BYTES_PER_S[key]
        return _DEFAULT_BYTES_PER_S["spill.h2d"]

    def price(self, name, flops=0, bytes_moved=0, dispatches=0):
        wall = dispatches * self.dispatch_s
        if flops:
            wall += flops / self.flops_rate(name)
        if bytes_moved:
            wall += bytes_moved / self.bytes_rate(name)
        return StageCost(name, int(flops), int(bytes_moved),
                         int(dispatches), wall)


def price_forward(inputs, coeffs, colpass=None):
    """Stage costs of one streamed forward pass over the cover."""
    from ..utils.flops import (
        forward_sampled_flops,
        resolve_colpass,
        sampled_facet_pass_flops,
    )

    core = inputs.base().core
    if colpass is None:
        colpass = resolve_colpass(core, inputs.n_facets)
    total = forward_sampled_flops(
        core, n_facets=inputs.n_facets, facet_size=inputs.yB,
        n_columns=inputs.n_columns,
        subgrids_per_column=inputs.subgrids_per_column,
        subgrid_size=inputs.xA, real_facets=inputs.real_facets,
        colpass=colpass,
    )
    facet_pass = sampled_facet_pass_flops(
        core, inputs.n_facets, inputs.yB, inputs.n_columns * inputs.m,
        real_facets=inputs.real_facets,
    )
    col_stage = "fwd.column_pass" + (
        ".pallas" if colpass == "pallas" else ""
    )
    return [
        coeffs.price("fwd.sampled_facet_pass", flops=facet_pass),
        coeffs.price(col_stage, flops=total - facet_pass),
    ]


def price_colpass_candidates(inputs, coeffs):
    """Ranked forward column-pass candidates (einsum vs pallas).

    Prices ONLY the column-pass stage of each body (the facet pass is
    identical) with that body's exact FLOP shape and its own coefficient
    stage name — so a refit pallas coefficient prices the pallas row
    with measured pedigree while einsum keeps its own. Returns dicts
    sorted fastest-first; the executor's `resolve_colpass` keeps the
    CHOICE (defaults only rank, the compiler's measured-coefficients
    rule), the ranking is recorded in the artifact for the operator.
    """
    from ..utils.flops import (
        forward_sampled_flops,
        sampled_facet_pass_flops,
    )

    core = inputs.base().core
    facet_pass = sampled_facet_pass_flops(
        core, inputs.n_facets, inputs.yB, inputs.n_columns * inputs.m,
        real_facets=inputs.real_facets,
    )
    out = []
    for colpass in ("einsum", "pallas"):
        total = forward_sampled_flops(
            core, n_facets=inputs.n_facets, facet_size=inputs.yB,
            n_columns=inputs.n_columns,
            subgrids_per_column=inputs.subgrids_per_column,
            subgrid_size=inputs.xA, real_facets=inputs.real_facets,
            colpass=colpass,
        )
        stage = "fwd.column_pass" + (
            ".pallas" if colpass == "pallas" else ""
        )
        cost = coeffs.price(stage, flops=total - facet_pass)
        out.append({
            "colpass": colpass,
            "coeff_stage": stage,
            "flops": int(total - facet_pass),
            "flops_per_s": coeffs.flops_rate(stage),
            "predicted_wall_s": round(cost.wall_s, 4),
        })
    out.sort(key=lambda c: c["predicted_wall_s"])
    return out


def price_collective_stage(coeffs, collective, bytes_moved):
    """The planned facet-axis collective as one priced `StageCost`.

    ``psum`` prices the blocking all-reduce at the ``mesh.psum`` rate.
    ``ring`` prices the same wire bytes at the ``mesh.ring_step`` rate
    and — when that rate is still the default anchor — applies the
    `RING_OVERLAP_DISCOUNT` (the hidden-behind-compute fraction). A
    MEASURED mesh.ring_step coefficient already is the exposed rate
    (the engine's stage timer runs after the overlapped work), so no
    discount stacks on top of it.
    """
    stage = "mesh.ring_step" if collective == "ring" else "mesh.psum"
    cost = coeffs.price(stage, bytes_moved=bytes_moved)
    if collective == "ring" and stage not in coeffs.bytes_per_s:
        cost.wall_s *= 1.0 - RING_OVERLAP_DISCOUNT
    return cost


def price_collective_candidates(inputs, coeffs, mesh=None,
                                mode="roundtrip-streamed"):
    """Ranked facet-axis collective candidates (psum vs ring).

    The mesh analogue of `price_colpass_candidates`: each schedule is
    priced over the SAME layout's collective bytes with its own
    coefficient stage as pedigree. The ring row carries the schedule
    shape — 2(shards-1) `ppermute` steps of per-chunk bytes (the
    per-column buffer split `shards` ways) — and the overlap discount
    applied (0 when a measured mesh.ring_step rate prices the exposed
    wall directly). Returns dicts sorted fastest-first; like the
    colpass table, defaults only RANK — the executor's
    `resolve_collective` (env) and the compiler's calibrated-gate keep
    the choice.
    """
    if mesh is None:
        from .compiler import plan_mesh_layout

        mesh = plan_mesh_layout(inputs, mode=mode)
    shards = int(mesh.facet_shards)
    total = int(mesh.collective_bytes_total)
    if shards <= 1 or not total:
        return []
    steps = 2 * (shards - 1)
    per_column = int(mesh.collective_bytes_per_column)
    out = []
    for collective in ("psum", "ring"):
        stage = "mesh.ring_step" if collective == "ring" else "mesh.psum"
        measured = stage in coeffs.bytes_per_s
        cost = price_collective_stage(coeffs, collective, total)
        out.append({
            "collective": collective,
            "coeff_stage": stage,
            "bytes": total,
            "steps": 1 if collective == "psum" else steps,
            "chunk_bytes": (
                per_column if collective == "psum"
                else per_column // max(1, steps * shards)
            ),
            "overlap_discount": (
                0.0 if collective == "psum" or measured
                else RING_OVERLAP_DISCOUNT
            ),
            "bytes_per_s": coeffs.bytes_rate(stage),
            "predicted_wall_s": round(cost.wall_s, 4),
        })
    out.sort(key=lambda c: c["predicted_wall_s"])
    return out


def price_backward(inputs, parts, fold_group, coeffs,
                   spill_fed=True, colpass=None, feed_group=1):
    """Stage costs of a facet x row-slab partitioned sampled backward.

    Every pass consumes the whole subgrid stream — but under the
    feed-once/fold-many schedule ``feed_group`` passes SHARE each feed
    (`parallel.streamed.feed_backward_passes`), so the stream crosses
    the wire once per FEED, not once per pass. With ``spill_fed`` the
    feeds after the first read the recorded stream back host->device
    (the ``bwd.feed_group`` stage, priced by bytes); without a usable
    cache each later feed replays the forward instead — still once per
    feed, the schedule helps the replay model identically. Fold FLOPs
    restrict with the pass's output-row slab (the "ri" index
    restriction is free). ``feed_group=1`` reproduces the pre-schedule
    per-pass-feed pricing exactly.
    """
    from ..utils.flops import (
        bwd_column_pass_flops,
        bwd_fold_flops,
        resolve_colpass_bwd,
    )

    core = inputs.base().core
    if colpass is None:
        colpass = resolve_colpass_bwd(core, inputs.n_facets)
    col_flops = fold_flops = 0
    for i0, i1, r0, r1 in parts:
        F_pass = i1 - i0
        col_flops += inputs.n_columns * bwd_column_pass_flops(
            core, F_pass, inputs.subgrids_per_column, inputs.yB,
            inputs.xA, colpass,
        )
        fold_flops += int(
            bwd_fold_flops(core, F_pass, inputs.yB,
                           inputs.n_columns * inputs.m)
            * (r1 - r0) / inputs.yB
        )
    n_passes = len(parts)
    n_feeds = -(-n_passes // max(1, int(feed_group)))
    folds_per_pass = -(-inputs.n_columns // max(1, fold_group))
    bwd_col_stage = "bwd.column_pass" + (
        ".pallas" if colpass == "pallas" else ""
    )
    stages = [
        coeffs.price(bwd_col_stage, flops=col_flops,
                     dispatches=n_passes * folds_per_pass),
        coeffs.price("bwd.sampled_fold", flops=fold_flops,
                     dispatches=n_passes * folds_per_pass),
    ]
    if spill_fed and n_feeds > 1:
        stages.append(
            coeffs.price("spill.write",
                         bytes_moved=inputs.stream_bytes)
        )
        stages.append(
            coeffs.price("bwd.feed_group",
                         bytes_moved=(n_feeds - 1) * inputs.stream_bytes,
                         dispatches=n_feeds - 1)
        )
    elif n_feeds > 1:
        # replay cost model: feeds 2..n re-run the forward (aggregated
        # into one stage — the per-feed split adds nothing)
        replays = price_forward(inputs, coeffs)
        stages.append(
            StageCost(
                "fwd.replay",
                (n_feeds - 1) * sum(s.flops for s in replays),
                (n_feeds - 1) * sum(s.bytes_moved for s in replays),
                (n_feeds - 1) * sum(s.dispatches for s in replays),
                (n_feeds - 1) * sum(s.wall_s for s in replays),
            )
        )
    return stages
