"""Unified plan compiler: one cost model for geometry, memory, schedule.

Three parts (ROADMAP item 4):

* ``plan.model`` — the declarative cost model: `PlanInputs` (N,
  facet/subgrid geometry, dtype, HBM budget, device count) priced into
  per-stage bytes/FLOPs/estimated wall via the same `utils.flops`
  formulas the obs instrumentation attributes with, plus the shared
  helpers the old forks each re-implemented (`hbm_budget_bytes`,
  `bucket_sizes`, the serve admission byte projections).
* ``plan.compiler`` — `compile_plan()` searches the model and emits one
  executable `Plan`: the backward facet x row-slab pass grid, the spill
  policy (RAM/disk/replay), serve bucket shapes + admission pricing,
  and the mesh layout (`plan_mesh_layout`: facet shards from device
  count + HBM budget, ICI collective bytes priced; bound by the
  mesh-streamed engine in `swiftly_tpu.mesh`). bench.py, the
  coalescing scheduler, the spill cache and the serve fleet are its
  consumers; seed-geometry plans are pinned equivalent to the old
  heuristics by tests/test_128k.py.
* ``plan.autotune`` — `refit(history)` reads provenance-stamped
  artifact history (PR-1 manifests + per-stage telemetry, PR-5 trace
  self-time) into measured per-stage throughput coefficients;
  `compile_plan(..., history=...)` then picks e.g. fold groups and
  slab counts from measured walls instead of static constants.

`scripts/plan_explain.py` prints a chosen plan, its predicted wall/HBM
peak and the rejected alternatives; see docs/planning.md.
"""

from . import autotune, compiler, model
from .autotune import (
    ledger_readiness,
    load_history,
    refit,
    refit_from_ledger,
)
from .compiler import (
    BackwardPlan,
    CacheTierPlan,
    DeltaPlan,
    MeshLayout,
    Plan,
    ServePlan,
    SpillPolicy,
    compile_plan,
    plan_backward_passes,
    plan_delta,
    plan_mesh_layout,
    price_cache_tier,
    stamp_measured_wall,
)
from .model import (
    CostCoefficients,
    PlanInputs,
    bucket_shape,
    bucket_sizes,
    hbm_budget_bytes,
    price_collective_candidates,
    price_colpass_candidates,
    projected_column_bytes,
    projected_request_bytes,
)
from .vis import VisPlan, price_vis

__all__ = [
    "BackwardPlan",
    "CacheTierPlan",
    "CostCoefficients",
    "DeltaPlan",
    "MeshLayout",
    "Plan",
    "PlanInputs",
    "ServePlan",
    "SpillPolicy",
    "VisPlan",
    "autotune",
    "bucket_shape",
    "bucket_sizes",
    "compile_plan",
    "compiler",
    "hbm_budget_bytes",
    "ledger_readiness",
    "load_history",
    "model",
    "plan_backward_passes",
    "plan_delta",
    "plan_mesh_layout",
    "price_cache_tier",
    "price_collective_candidates",
    "price_colpass_candidates",
    "price_vis",
    "projected_column_bytes",
    "projected_request_bytes",
    "refit_from_ledger",
    "stamp_measured_wall",
]
