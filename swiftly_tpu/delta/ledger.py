"""`FacetDeltaLedger`: content-addressed facet-stack versioning.

The incremental re-transform engine (`delta.engine`) patches a recorded
subgrid stream instead of recomputing it — but a patch is only valid
against the EXACT facet stack the stream was recorded for. The ledger
is that provenance: it content-hashes every facet per committed
version, detects which facets changed between a committed version and a
proposed stack, and stamps a monotone ``stream_version`` into the spill
cache (and checkpoint meta) so every consumer — `CachedColumnFeed`, the
serve path, a restored checkpoint — can refuse data recorded for a
stack that is no longer current.

Each facet is versioned as a (config, data) PAIR: `config_hash` covers
the `FacetConfig`'s identity — offsets, size, ownership masks — so a
facet whose geometry changes under identical data still invalidates
the stream (and is reported by ``config_changed`` so the engine
replays instead of mis-pairing the old config with a data diff).

Data hashing is by CONTENT, not identity: a facet rebuilt from the same
sources hashes equal (no spurious invalidation), a one-pixel change
hashes different (no stale serve). Sparse facets
(`ops.oracle.SparseRealFacet`) hash their coordinate/value arrays
directly — at 64k that is a few hundred bytes instead of a 2 GB dense
plane. Callable (lazy) facet tasks are materialised for hashing, the
same contract `parallel.streamed.StreamedForward` applies.
"""

from __future__ import annotations

import hashlib

import numpy as np

__all__ = ["FacetDeltaLedger", "config_hash", "facet_hash"]


def config_hash(fc):
    """Identity hash of one facet's `models.config.FacetConfig` — the
    geometry the data is recorded against (offsets, size, ownership
    masks; masks realised for hashing, so a slice-list and its realised
    array hash equal). A facet whose config changes while its data
    stays identical is NOT the same facet: the facet→subgrid map
    depends on both, so the ledger versions the pair."""
    h = hashlib.sha256()
    if fc is None:
        h.update(b"config:none")
        return h.hexdigest()
    h.update(
        f"config:off0={int(fc.off0)};off1={int(fc.off1)};"
        f"size={int(fc.size)};".encode()
    )
    for name in ("mask0", "mask1"):
        mask = getattr(fc, name, None)
        if mask is None:
            h.update(f"{name}:none;".encode())
        else:
            arr = np.ascontiguousarray(np.asarray(mask))
            h.update(f"{name}:{arr.shape}:{arr.dtype};".encode())
            h.update(arr.tobytes())
    return h.hexdigest()


def facet_hash(data):
    """Content hash of one facet's data (sparse descriptor, dense
    array, or a callable returning either)."""
    from ..ops.oracle import SparseRealFacet

    if callable(data):
        data = data()
    h = hashlib.sha256()
    if isinstance(data, SparseRealFacet):
        h.update(b"sparse:")
        h.update(str(int(data.size)).encode())
        h.update(np.ascontiguousarray(data.rows).tobytes())
        h.update(np.ascontiguousarray(data.cols).tobytes())
        h.update(np.ascontiguousarray(data.vals).tobytes())
    else:
        arr = np.asarray(data)
        h.update(b"dense:")
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


class FacetDeltaLedger:
    """Versioned content hashes of a facet stack.

    ``commit(facet_tasks)`` records the stack and bumps ``version``
    IFF the content changed (committing an identical stack is a no-op,
    so re-running a pipeline never invalidates a valid cache);
    ``changed(facet_tasks)`` lists the facet indices whose content
    differs from the last committed version; ``stamp(cache)`` writes
    the current version onto any object with a ``stream_version``
    attribute (the `utils.spill.SpillCache` contract).

    Versions start at 0 (nothing committed) and are strictly monotone —
    a consumer that recorded version v can treat ANY other value as
    stale, not just larger ones.
    """

    def __init__(self):
        self.version = 0
        self._hashes = None

    @property
    def n_facets(self):
        return None if self._hashes is None else len(self._hashes)

    def commit(self, facet_tasks):
        """Record ``facet_tasks`` as the current stack; returns the
        (possibly bumped) version. Each facet is hashed as a
        (config, data) PAIR — a config-only change versions the stack
        exactly like a data change (the recorded stream is stale either
        way)."""
        hashes = self._pair_hashes(facet_tasks)
        if self._hashes is None or hashes != self._hashes:
            self.version += 1
        self._hashes = hashes
        return self.version

    def changed(self, facet_tasks):
        """Indices of facets whose content OR config differs from the
        committed stack. Requires a prior ``commit`` and an equal facet
        count — a cover change is not a delta, it is a different
        stream."""
        pairs = self._pair_hashes(facet_tasks, require_committed=True)
        return [
            j for j, (a, b) in enumerate(zip(self._hashes, pairs))
            if a != b
        ]

    def config_changed(self, facet_tasks):
        """Indices of facets whose CONFIG (geometry/masks) differs from
        the committed stack. A changed config is never a data delta —
        the facet→subgrid map depends on it, so
        `delta.IncrementalForward` replays instead of patching. Same
        preconditions as `changed`."""
        pairs = self._pair_hashes(facet_tasks, require_committed=True)
        return [
            j for j, ((ca, _da), (cb, _db))
            in enumerate(zip(self._hashes, pairs))
            if ca != cb
        ]

    def _pair_hashes(self, facet_tasks, require_committed=False):
        """(config_hash, facet_hash) per facet, with the shared
        precondition checks."""
        if require_committed:
            if self._hashes is None:
                raise ValueError(
                    "no committed facet stack; commit() (or "
                    "IncrementalForward.record()) must run before "
                    "changed()"
                )
            if len(facet_tasks) != len(self._hashes):
                raise ValueError(
                    f"facet count changed ({len(self._hashes)} -> "
                    f"{len(facet_tasks)}); an incremental update "
                    "requires the same cover — re-record the stream"
                )
        return [
            (config_hash(fc), facet_hash(d)) for fc, d in facet_tasks
        ]

    def stamp(self, cache):
        """Write the current version onto ``cache.stream_version``;
        returns the version."""
        cache.stream_version = self.version
        return self.version

    def as_dict(self):
        """JSON-ready summary for artifacts/checkpoint meta."""
        return {
            "version": int(self.version),
            "n_facets": self.n_facets,
        }
