"""`IncrementalForward`: facet-delta updates that patch the recorded
subgrid stream instead of recomputing it.

The facet -> subgrid map ``S_i = A_i sum_j ( n_j * m_i (b_j * F_j) )``
is LINEAR in the facets: a changed facet contributes additively, so for
K changed facets of J the correction to every subgrid is exactly a
streamed forward over the K delta facets ``dF_j = F_j_new - F_j_old``
— ~K/J of a full forward's compute — added into the recorded stream.
This engine wraps `parallel.streamed.StreamedForward` with that
workflow:

1. ``record(subgrid_configs)`` runs one full streamed forward,
   persisting the stream into a `utils.spill.SpillCache` and committing
   the facet stack to a `delta.ledger.FacetDeltaLedger`;
2. ``update(new_facet_tasks)`` detects the changed facets by content
   hash, streams the column passes with the facet stack RESTRICTED to
   those K deltas, routes every correction row onto its recorded cache
   position via the spill metadata's input indices (robust to the delta
   pass choosing a different column grouping than the recording run),
   and patches each cache entry in place — one atomic
   `SpillCache.patch_entry` per group (RAM in-place add, or disk
   tmp-sibling + rename) — then bumps the ledger's ``stream_version``
   into the cache so stale feeds invalidate.

Exactness contract (docs/incremental.md): the patched stream equals a
full recompute up to f32 sum-reorder error — the delta adds facet
contributions in a different association order than the fused
column-pass einsum. ``SWIFTLY_DELTA_EXACT=1`` (or ``exact=True``)
re-records the stream from scratch with the new stack instead:
bit-identical to a fresh forward, at full-forward cost — the
correctness escape hatch, not the fast path.

Failure posture (the PR-4 degradation ladder): ANY failure on the
patch path — a delta-stream error, an unmappable row, a patch write
that stays failed past its retries — degrades to a full re-record of
the stream with the new stack (``delta.patch_to_replay`` in the
degradation ledger). Slower, never wrong; a partially-patched cache is
impossible to observe: the replay re-fills every entry, and for the
whole rewrite window (first ``patch_entry`` through the version
re-stamp, and the replay's reset-to-refill) the cache is marked
mid-patch (`utils.spill.SpillCache.begin_patch`), so a CONCURRENT
consumer — a live `CachedColumnFeed` on a serving replica — gets
LookupError and falls back to compute at its pinned version instead of
reading a torn mix of old and new rows. A facet whose `FacetConfig`
(geometry/masks) changed is NOT treated as a data delta — the
facet→subgrid map depends on the config, so the engine replays
(``facet_config_changed``) rather than pairing the old config with a
data diff.

Break-even: `plan.plan_delta` prices the incremental path against the
full recompute from the same stage coefficients; ``update`` honours
the cheaper choice (and records the plan in its report).
"""

from __future__ import annotations

import logging
import os

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..parallel.streamed import CachedColumnFeed, StreamedForward
from ..resilience import degrade as _degrade
from .ledger import FacetDeltaLedger

__all__ = ["IncrementalForward", "facet_delta"]

logger = logging.getLogger(__name__)


def facet_delta(old, new):
    """``new - old`` for one facet's data, keeping sparse descriptors
    sparse (the concatenated coordinate lists with negated old values —
    duplicates accumulate in both the host densify and the device
    scatter, so the result is exact)."""
    from ..ops.oracle import SparseRealFacet

    old = old() if callable(old) else old
    new = new() if callable(new) else new
    if isinstance(old, SparseRealFacet) and isinstance(new, SparseRealFacet):
        if old.size != new.size:
            raise ValueError(
                f"facet size changed ({old.size} -> {new.size}); "
                "not a delta"
            )
        return SparseRealFacet(
            new.size,
            np.concatenate([new.rows, old.rows]),
            np.concatenate([new.cols, old.cols]),
            np.concatenate([new.vals, -np.asarray(old.vals)]),
        )
    if isinstance(old, SparseRealFacet):
        old = old.densify()
    if isinstance(new, SparseRealFacet):
        new = new.densify()
    old = np.asarray(old)
    new = np.asarray(new)
    if old.shape != new.shape:
        raise ValueError(
            f"facet shape changed ({old.shape} -> {new.shape}); "
            "not a delta"
        )
    return new - old


class IncrementalForward:
    """A streamed forward whose recorded output stream accepts
    facet-delta patches.

    :param swiftly_config: `SwiftlyConfig` (device backend)
    :param facet_tasks: list of (FacetConfig, facet_data) pairs —
        callables are materialised (the ledger hashes content)
    :param spill: the `utils.spill.SpillCache` holding the recorded
        stream (the memo the updates patch)
    :param ledger: a `FacetDeltaLedger` (default: fresh)
    :param col_group / facet_group: forwarded to `StreamedForward`
    """

    def __init__(self, swiftly_config, facet_tasks, spill, ledger=None,
                 col_group=None, facet_group=None):
        self.config = swiftly_config
        self.facet_tasks = [
            (fc, d() if callable(d) else d) for fc, d in facet_tasks
        ]
        self.spill = spill
        self.ledger = ledger or FacetDeltaLedger()
        self._col_group = col_group
        self._facet_group = facet_group
        self.fwd = self._make_fwd(self.facet_tasks)
        self._subgrid_configs = None
        self.last_report = None

    def _make_fwd(self, tasks):
        return StreamedForward(
            self.config, tasks, residency="device",
            col_group=self._col_group, facet_group=self._facet_group,
        )

    # -- record -------------------------------------------------------------

    def record(self, subgrid_configs):
        """Run one full streamed forward, persisting the stream; commits
        the facet stack and stamps the stream version. Re-recording
        (e.g. after an update chose replay) starts from a reset cache."""
        self._subgrid_configs = list(subgrid_configs)
        if len(self.spill):
            self.spill.reset()
        for _ in self.fwd.stream_column_groups(
            self._subgrid_configs, spill=self.spill
        ):
            pass
        if not self.spill.complete:
            raise RuntimeError(
                "the stream did not fit the spill cache (fill gave up); "
                "incremental updates need a complete recording — raise "
                "SWIFTLY_SPILL_BUDGET_GB or set SWIFTLY_SPILL_DIR"
            )
        self.ledger.commit(self.facet_tasks)
        self.ledger.stamp(self.spill)
        _trace.instant("delta.record", cat="delta",
                       version=self.ledger.version,
                       entries=len(self.spill))
        return {"stream_version": self.ledger.version,
                "entries": len(self.spill)}

    def feed(self):
        """A fresh `CachedColumnFeed` over the recorded stream, pinned
        to the CURRENT stream version."""
        return CachedColumnFeed(self.spill)

    def fabric(self, *, l1_rows=64):
        """A `cache.SharedStreamTier` over the recorded stream: ONE
        resident, versioned L2 that N serve replicas front with hot-row
        L1 views (`SharedStreamTier.view`). After `update`, roll the
        fabric (`SharedStreamTier.roll` with the update report) instead
        of re-building per-replica feeds — `serve.ServeFleet` does this
        when constructed with ``fabric=``."""
        from ..cache import SharedStreamTier

        return SharedStreamTier(self.spill, l1_rows=l1_rows)

    # -- update -------------------------------------------------------------

    def update(self, new_facet_tasks, exact=None, use_plan=True):
        """Adopt ``new_facet_tasks``; patch or re-record the stream.

        Returns a report dict: ``mode`` ("patch" | "replay" | "noop"),
        ``reason`` (why replay/noop), ``changed_facets``,
        ``patched_columns`` / ``patched_entries``, ``stream_version``
        and ``plan`` (the `plan.plan_delta` pricing, when available).
        """
        if self._subgrid_configs is None:
            raise ValueError("record() must run before update()")
        tasks = [
            (fc, d() if callable(d) else d) for fc, d in new_facet_tasks
        ]
        changed = self.ledger.changed(tasks)
        if not changed:
            self.last_report = {
                "mode": "noop", "reason": "no_facets_changed",
                "changed_facets": [], "patched_columns": 0,
                "patched_entries": 0,
                "stream_version": self.ledger.version, "plan": None,
            }
            return self.last_report
        if exact is None:
            exact = os.environ.get("SWIFTLY_DELTA_EXACT") == "1"
        plan_dict = self._plan(len(changed)) if use_plan else None
        reason = None
        if exact:
            reason = "exact_mode"
        elif self.ledger.config_changed(tasks):
            # a config change is not a data delta: the facet->subgrid
            # map depends on the geometry/masks, so pairing the old
            # config with a data diff would silently mis-stream the
            # correction — replay with the new stack instead
            reason = "facet_config_changed"
        elif not self.spill.complete:
            reason = "incomplete_cache"
        elif len(changed) >= len(tasks):
            reason = "all_facets_changed"
        elif plan_dict is not None and plan_dict.get("mode") == "full":
            reason = "plan_break_even"
        if reason is not None:
            return self._replay(tasks, changed, reason, plan_dict)
        try:
            corrections, patched_columns = self._stream_delta(
                tasks, changed
            )
            # live feeds refuse lookups from the first patched entry
            # until the bumped version is stamped (begin_patch /
            # end_patch): a consumer racing the patch — a serving
            # replica's CachedColumnFeed — can never return a mix of
            # old and new rows; it falls back to compute at the
            # version its request was admitted under
            self.spill.begin_patch()
            try:
                for k in sorted(corrections):
                    self.spill.patch_entry(k, corrections[k])
                self._adopt(tasks)
                self.ledger.commit(self.facet_tasks)
                self.ledger.stamp(self.spill)
            finally:
                self.spill.end_patch()
        except Exception as exc:  # noqa: BLE001 - the degradation ladder
            # rung: patch -> replay. A torn patch (some entries updated,
            # some not) is unobservable: the replay re-fills every entry
            # from the new stack.
            logger.warning(
                "incremental patch failed (%s: %s); replaying the full "
                "forward with the new facet stack",
                type(exc).__name__, exc,
            )
            _degrade.record(
                "delta", "patch_to_replay",
                f"{type(exc).__name__}: {exc}",
            )
            _metrics.count("delta.patch_failures")
            return self._replay(
                tasks, changed, "patch_failed", plan_dict
            )
        _metrics.count("delta.patches")
        _metrics.count("delta.patched_entries", len(corrections))
        _trace.instant("delta.patch", cat="delta",
                       changed=len(changed),
                       entries=len(corrections),
                       version=self.ledger.version)
        self.last_report = {
            "mode": "patch", "reason": None,
            "changed_facets": list(changed),
            "patched_columns": int(patched_columns),
            "patched_entries": len(corrections),
            "stream_version": self.ledger.version,
            "plan": plan_dict,
        }
        return self.last_report

    # -- internals ----------------------------------------------------------

    def _plan(self, n_changed):
        """Price incremental vs full via `plan.plan_delta`; None when
        the geometry cannot be priced (pricing is advisory — the engine
        still has the exactness ladder either way)."""
        try:
            from ..plan import PlanInputs, plan_delta

            inputs = PlanInputs.from_cover(
                self.config,
                [fc for fc, _ in self.facet_tasks],
                self._subgrid_configs,
            )
            return plan_delta(inputs, n_changed).as_dict()
        except Exception as exc:  # noqa: BLE001 - pricing is advisory
            logger.debug("plan_delta unavailable: %s", exc)
            return None

    def _stream_delta(self, tasks, changed):
        """Stream the K delta facets; return ``{entry_k: correction}``
        (one dense [G, S, ...] addend per cache entry) plus the number
        of distinct columns the corrections touch."""
        delta_tasks = [
            (self.facet_tasks[j][0],
             facet_delta(self.facet_tasks[j][1], tasks[j][1]))
            for j in changed
        ]
        dfwd = self._make_fwd(delta_tasks)
        # Cache positions by the cover's input index: the delta pass may
        # group columns differently than the recording run (its column
        # grouping auto-sizes from K facets, not J), so rows are routed
        # by identity, never by position.
        pos = {}
        for k in range(len(self.spill)):
            for c, col in enumerate(self.spill.meta(k)):
                for s, (i, _sg) in enumerate(col):
                    pos[int(i)] = (k, c, s)
        corrections = {}
        columns = set()
        for per_col, out_g in dfwd.stream_column_groups(
            self._subgrid_configs
        ):
            with _metrics.stage("delta.d2h") as st:
                host = np.asarray(out_g)
                st.bytes_moved = int(host.nbytes)
            for c, col in enumerate(per_col):
                for s, (i, _sg) in enumerate(col):
                    k, cc, ss = pos[int(i)]
                    corr = corrections.get(k)
                    if corr is None:
                        corr = corrections[k] = np.zeros(
                            self.spill.get(k).shape, dtype=host.dtype
                        )
                    corr[cc, ss] += host[c, s]
                    columns.add((k, cc))
        return corrections, len(columns)

    def _replay(self, tasks, changed, reason, plan_dict):
        """Full re-record with the new stack — the exact path and the
        ladder's landing zone. Bit-identical to a fresh forward. Live
        feeds refuse lookups for the whole reset-to-refill window
        (``begin_patch`` plus the cache's own ``complete`` gate), and a
        refill that overflows the budget raises BEFORE the ledger
        commits — mirroring `record`'s check — so a half-recorded
        stream is never reported as a successful replay."""
        self._adopt(tasks)
        self.spill.begin_patch()
        try:
            self.spill.reset()
            for _ in self.fwd.stream_column_groups(
                self._subgrid_configs, spill=self.spill
            ):
                pass
            if not self.spill.complete:
                raise RuntimeError(
                    "the replay stream did not fit the spill cache "
                    "(fill gave up); the recorded stream is incomplete "
                    "and feeds fall back to compute — raise "
                    "SWIFTLY_SPILL_BUDGET_GB or set SWIFTLY_SPILL_DIR, "
                    "then record() again"
                )
            self.ledger.commit(self.facet_tasks)
            self.ledger.stamp(self.spill)
        finally:
            self.spill.end_patch()
        _metrics.count("delta.replays")
        _trace.instant("delta.replay", cat="delta", reason=reason,
                       version=self.ledger.version)
        self.last_report = {
            "mode": "replay", "reason": reason,
            "changed_facets": list(changed),
            "patched_columns": 0, "patched_entries": 0,
            "stream_version": self.ledger.version,
            "plan": plan_dict,
        }
        return self.last_report

    def _adopt(self, tasks):
        self.facet_tasks = tasks
        self.fwd = self._make_fwd(tasks)
