"""Incremental re-transform engine (ROADMAP item 5b).

The facet -> subgrid map is linear in the facets, so a K-of-J facet
update costs ~K/J of a streamed forward plus a patch of the recorded
subgrid stream — this package is that update path:

* `ledger.FacetDeltaLedger` — content-hashed facet-stack versioning;
  the monotone ``stream_version`` it stamps into the spill cache is
  what invalidates stale feeds and checkpoints.
* `engine.IncrementalForward` — record once, then ``update()`` streams
  only the changed facets' deltas and patches the cached stream in
  place (falling back to a full re-record on any patch failure, and
  under ``SWIFTLY_DELTA_EXACT=1``).

See docs/incremental.md; `plan.plan_delta` prices the break-even and
``bench.py --delta`` is the measured drill.
"""

from .engine import IncrementalForward, facet_delta
from .ledger import FacetDeltaLedger, config_hash, facet_hash

__all__ = [
    "FacetDeltaLedger",
    "IncrementalForward",
    "config_hash",
    "facet_delta",
    "facet_hash",
]
