"""swiftly-tpu: TPU-native streaming distributed Fourier transform.

Bidirectional facet <-> subgrid transforms between image space and uv-grid
space that never materialise the full N x N plane, built from scratch for
TPU (JAX/XLA; planar-complex matmul FFT; facet-sharded device meshes with
psum reductions). Capability parity with
ska-telescope/ska-sdp-distributed-fourier-transform ("SwiFTly").
"""

from .api import (
    FacetConfig,
    FlightQueue,
    LRUCache,
    SubgridConfig,
    SwiftlyBackward,
    SwiftlyConfig,
    SwiftlyForward,
    backward_all,
    check_facet,
    check_residual,
    check_subgrid,
    make_facet,
    make_real_facet,
    make_full_facet_cover,
    make_full_subgrid_cover,
    make_sparse_facet,
    make_sparse_facet_cover,
    make_subgrid,
    sparse_fov_cover_offsets,
)
from .models import SWIFT_CONFIGS
from .ops import (
    SwiftlyCore,
    make_facet_from_sources,
    make_subgrid_from_sources,
)

__version__ = "0.1.0"

__all__ = [
    "FacetConfig",
    "FlightQueue",
    "LRUCache",
    "SWIFT_CONFIGS",
    "SubgridConfig",
    "SwiftlyBackward",
    "SwiftlyConfig",
    "SwiftlyCore",
    "SwiftlyForward",
    "backward_all",
    "check_facet",
    "check_residual",
    "check_subgrid",
    "make_facet",
    "make_real_facet",
    "make_facet_from_sources",
    "make_full_facet_cover",
    "make_full_subgrid_cover",
    "make_sparse_facet",
    "make_sparse_facet_cover",
    "make_subgrid",
    "make_subgrid_from_sources",
    "sparse_fov_cover_offsets",
]
