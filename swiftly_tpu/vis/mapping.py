"""Sample -> owning-subgrid mapping for visibility serving.

A degrid sample at fractional (u, v) needs a ``support x support``
patch of integer grid pixels around it, all inside ONE served subgrid
(and inside that subgrid's mask-1 region — masked-out border pixels
are zeros, not grid values). `VisCoverIndex` precomputes, per axis,
the sorted span table of the subgrid cover and answers, per sample:

* the owning ``(off0, off1)`` subgrid and the patch's first-tap index
  into its rows, or
* *outside_cover* — the patch straddles a subgrid boundary (or falls
  off the cover / into a masked border). Those samples are SHED with
  ``shed_reason="outside_cover"`` (`vis.service`), never answered
  wrong: the cover's column overlap is a deployment choice, and the
  structured shed tells the operator which margin to widen.

Coordinates are grid pixels (the subgrid axes of
`ops.oracle.make_subgrid_from_sources`: column ``off`` spans
``[off - size/2, off + size/2)``), periodic in N; inputs are
canonicalised into the cover's principal window first.
"""

from __future__ import annotations

import numpy as np

__all__ = ["VisCoverIndex"]


def _axis_spans(offs, sizes, masks):
    """Sorted (lo, hi_exclusive, off, mask_lo, mask_hi) spans for one
    axis of the cover; the mask bounds are the contiguous mask-1 run
    (full covers are all-ones -> the whole span)."""
    spans = []
    for off, size, mask in zip(offs, sizes, masks):
        lo = off - size // 2
        m_lo, m_hi = lo, lo + size
        if mask is not None:
            m = np.asarray(mask)
            ones = np.flatnonzero(m != 0)
            if ones.size == 0:
                continue
            m_lo = lo + int(ones[0])
            m_hi = lo + int(ones[-1]) + 1
        spans.append((lo, lo + size, int(off), m_lo, m_hi))
    spans.sort()
    return spans


class VisCoverIndex:
    """Owning-subgrid lookup over a subgrid cover.

    :param subgrid_configs: the cover (`models.covers
        .make_full_subgrid_cover` or any SubgridConfig list)
    :param support: kernel tap count (`vis.kernel.VisKernel.support`)
    :param N: grid period (``config.image_size``) for canonicalisation
    """

    def __init__(self, subgrid_configs, support, N):
        self.support = int(support)
        self.N = int(N)
        self.taps_lo = -(self.support // 2 - 1)
        self.taps_hi = self.support // 2  # inclusive
        by_key = {}
        for sg in subgrid_configs:
            by_key[(sg.off0, sg.off1)] = sg
        self._configs = by_key
        offs0 = sorted({sg.off0 for sg in subgrid_configs})
        offs1 = sorted({sg.off1 for sg in subgrid_configs})
        sg0 = {sg.off0: sg for sg in subgrid_configs}
        sg1 = {sg.off1: sg for sg in subgrid_configs}
        self._spans_u = _axis_spans(
            offs0,
            [sg0[o].size for o in offs0],
            [sg0[o].mask0 for o in offs0],
        )
        self._spans_v = _axis_spans(
            offs1,
            [sg1[o].size for o in offs1],
            [sg1[o].mask1 for o in offs1],
        )
        if not self._spans_u or not self._spans_v:
            raise ValueError("empty subgrid cover")
        # principal window: [first span lo, first span lo + N)
        self._win_lo = self._spans_u[0][0]

    def config(self, off0, off1):
        return self._configs[(off0, off1)]

    def canonicalise(self, uv):
        """(u, v) folded into the cover's principal window (period N)."""
        uv = np.asarray(uv, dtype=float)
        return (uv - self._win_lo) % self.N + self._win_lo

    def _owner_1d(self, spans, x0):
        """Axis owner of integer first-pixel coordinate ``x0`` whose
        taps span [x0, x0 + support); None when the patch crosses a
        span (or mask) boundary."""
        pat_lo = x0 + 0  # first tap
        pat_hi = x0 + self.support - 1  # last tap, inclusive
        # linear scan is fine: covers hold O(10) columns per axis; a
        # bisect would save nothing at these sizes
        for (lo, hi, off, m_lo, m_hi) in spans:
            if pat_lo >= m_lo and pat_hi < m_hi:
                return off, lo
        return None

    def map_samples(self, uv):
        """Partition a sample batch by owning subgrid.

        :param uv: [B, 2] fractional grid coordinates
        :return: ``(owners, shed_idx)`` — ``owners`` maps
            ``(off0, off1) -> dict`` with ``idx`` (input indices),
            ``iu0``/``iv0`` (first-tap row indices into the owning
            subgrid), ``fu``/``fv`` (sub-pixel fractions in [0, 1));
            ``shed_idx`` the outside-cover input indices
        """
        uv = self.canonicalise(np.atleast_2d(uv))
        u0 = np.floor(uv[:, 0]).astype(int)
        v0 = np.floor(uv[:, 1]).astype(int)
        fu = uv[:, 0] - u0
        fv = uv[:, 1] - v0
        owners, shed = {}, []
        for b in range(uv.shape[0]):
            first_u = u0[b] + self.taps_lo
            first_v = v0[b] + self.taps_lo
            own_u = self._owner_1d(self._spans_u, first_u)
            own_v = self._owner_1d(self._spans_v, first_v)
            key = None
            if own_u is not None and own_v is not None:
                key = (own_u[0], own_v[0])
                if key not in self._configs:
                    key = None  # sparse cover: axis spans exist but
                    # the (off0, off1) tile does not
            if key is None:
                shed.append(b)
                continue
            entry = owners.setdefault(
                key,
                {"idx": [], "iu0": [], "iv0": [], "fu": [], "fv": []},
            )
            entry["idx"].append(b)
            entry["iu0"].append(first_u - own_u[1])
            entry["iv0"].append(first_v - own_v[1])
            entry["fu"].append(fu[b])
            entry["fv"].append(fv[b])
        for entry in owners.values():
            for k in ("idx", "iu0", "iv0"):
                entry[k] = np.asarray(entry[k], dtype=int)
            for k in ("fu", "fv"):
                entry[k] = np.asarray(entry[k], dtype=float)
        return owners, shed
