"""Batched degridding: gather + small dense contraction, jitted.

One dispatch answers every sample of one served subgrid row: gather the
[B, W, W] pixel patches, then contract each against the separable tap
weights — ``vis[b] = sum_ij patch[b, i, j] * cu[b, i] * cv[b, j]``.
Real arithmetic throughout (tap weights are real, rows arrive as
real/imag planes), which is also what makes `vis.grid` the EXACT
adjoint: the same gather indices and the same real weights, transposed.

Batch sizes are padded to power-of-two buckets (the serve scheduler's
bucket discipline, `serve.scheduler.bucket_shape`) so the jit cache
holds O(log max_batch) programs per subgrid shape instead of one per
request size.

The contraction runs as XLA einsums by default — CPU tier-1 exercises
the same program the TPU runs. ``SWIFTLY_PALLAS=1`` selects a fused
Pallas kernel for the weight outer-product + patch reduction (one VMEM
pass per B-block instead of materialising the [B, W, W] weight plane in
HBM); ``SWIFTLY_PALLAS_INTERPRET=1`` runs it in interpreter mode so the
CPU tier can equivalence-test the kernel (`ops.pallas_kernels`
discipline).
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops.pallas_kernels import pallas_enabled, pallas_interpret

__all__ = ["bucket_size", "degrid_batch", "split_row_planes"]

_MAX_BUCKET = 4096


def bucket_size(n, max_bucket=_MAX_BUCKET):
    """Smallest power-of-two >= n (capped) — the jit-cache bucket.

    The floor is 2, not 1: XLA compiles the B=1 einsum with a
    different reduction order than every B>=2 bucket (measured ~1ulp
    drift), which would break the contract that a sample's bits do not
    depend on how its batch was coalesced. Padding the singleton to a
    2-lane bucket keeps every bucket on the same vectorised program
    family, so per-lane results are bitwise identical across buckets.
    """
    b = 2
    while b < n and b < max_bucket:
        b *= 2
    return b


def split_row_planes(row):
    """A served subgrid row as (real, imag) float planes.

    Accepts the three layouts the serve path produces: planar
    ``[..., 2]`` host/device arrays (the planar backend and every
    recorded stream of it), complex arrays (jax/numpy backends), and
    real arrays (imag plane zero).
    """
    arr = np.asarray(row)
    if arr.ndim == 3 and arr.shape[-1] == 2:
        return arr[..., 0], arr[..., 1]
    if np.iscomplexobj(arr):
        return np.ascontiguousarray(arr.real), np.ascontiguousarray(
            arr.imag
        )
    return arr, np.zeros_like(arr)


@functools.lru_cache(maxsize=None)
def _degrid_fn(support, use_pallas):
    """Jitted [B]-bucket degrid body for one tap count."""
    import jax
    import jax.numpy as jnp

    offs = jnp.arange(support)

    def gather(plane, iu0, iv0):
        iu = iu0[:, None] + offs  # [B, W]
        iv = iv0[:, None] + offs
        return plane[iu[:, :, None], iv[:, None, :]]  # [B, W, W]

    if not use_pallas:

        def body(row_r, row_i, iu0, iv0, cu, cv):
            pr = gather(row_r, iu0, iv0)
            pi = gather(row_i, iu0, iv0)
            vr = jnp.einsum("bij,bi,bj->b", pr, cu, cv)
            vi = jnp.einsum("bij,bi,bj->b", pi, cu, cv)
            return vr, vi

        return jax.jit(body)

    from jax.experimental import pallas as pl

    def kernel(pr_ref, pi_ref, cu_ref, cv_ref, vr_ref, vi_ref):
        # one VMEM pass: weight outer product and both plane
        # reductions fused per B-block (VPU work; W*W is tiny, the
        # win is never materialising [B, W, W] weights in HBM)
        w2 = cu_ref[:, :, None] * cv_ref[:, None, :]
        vr_ref[...] = jnp.sum(pr_ref[...] * w2, axis=(1, 2))
        vi_ref[...] = jnp.sum(pi_ref[...] * w2, axis=(1, 2))

    def body(row_r, row_i, iu0, iv0, cu, cv):
        pr = gather(row_r, iu0, iv0)
        pi = gather(row_i, iu0, iv0)
        out = jax.ShapeDtypeStruct((pr.shape[0],), pr.dtype)
        return pl.pallas_call(
            kernel,
            out_shape=(out, out),
            interpret=pallas_interpret(),
        )(pr, pi, cu, cv)

    return jax.jit(body)


def degrid_batch(row, iu0, iv0, cu, cv, *, support=None):
    """Degrid one sample batch off one served subgrid row.

    :param row: the served row ([size, size] complex / real /
        planar ``[..., 2]``)
    :param iu0/iv0: [B] first-tap indices into the row (from
        `vis.mapping.VisCoverIndex.map_samples`)
    :param cu/cv: [B, W] separable tap weights
        (`vis.kernel.VisKernel.weights`)
    :return: [B] complex128 visibilities (host)

    The same jitted body serves cache-fed host rows and
    compute-fallback device rows: identical row BITS in give identical
    sample bits out, which is what makes the cache-vs-compute
    bit-identity contract of `serve` carry over to samples
    (tests/test_vis.py pins it).
    """
    row_r, row_i = split_row_planes(row)
    n = int(np.asarray(iu0).size)
    W = int(cu.shape[1]) if support is None else int(support)
    b = bucket_size(n)
    dt = row_r.dtype
    iu0_p = np.zeros(b, dtype=np.int32)
    iv0_p = np.zeros(b, dtype=np.int32)
    cu_p = np.zeros((b, W), dtype=dt)
    cv_p = np.zeros((b, W), dtype=dt)
    iu0_p[:n] = iu0
    iv0_p[:n] = iv0
    cu_p[:n] = cu
    cv_p[:n] = cv
    fn = _degrid_fn(W, pallas_enabled() or pallas_interpret())
    vr, vi = fn(row_r, row_i, iu0_p, iv0_p, cu_p, cv_p)
    return np.asarray(vr)[:n] + 1j * np.asarray(vi)[:n]
