"""PSWF-derived separable degridding kernel (host-side precompute).

Visibility serving answers arbitrary fractional (u, v) samples off the
integer-pixel subgrid rows the engine already serves. Truncated-support
interpolation of a DFT is fundamentally limited unless the IMAGE is
pre-shaped for it, so the kernel here is the classical gridding pair:

* **Grid correction (image space)** — the sky model is divided, per
  axis, by the kernel's *taper* (its continuous Fourier transform), so
  the grid the engine computes carries exactly the spectrum the
  truncated kernel can reconstruct. `grid_correction` /
  `correct_sources` apply it; `swiftly_tpu.vis.oracle.corrected_sources`
  is the bench/test entry.
* **Interpolation weights (grid space)** — for each sub-pixel fraction
  ``f`` the ``support`` weights are the least-squares solution of

      sum_d  c_d  exp(2 pi i d xi)  ~=  taper(xi) exp(2 pi i f xi)

  over the represented image band ``|xi| <= band / 2`` (xi = x / N).
  The target carries the taper, so interpolation error and correction
  cancel to quadrature accuracy instead of compounding. The taper is
  the quadrature Fourier transform of the same zeroth-order PSWF window
  `ops.pswf` builds the facet machinery from (``c = pi W / 2``,
  ``psi(2 t / W)`` on ``|t| <= W/2``) — the anti-aliasing pedigree the
  paper's window brings carries over to the serving path unchanged.

The weights are tabulated at ``oversample`` fractions and linearly
interpolated at lookup (`weights`). Measured worst-case relative error
of the full degrid path against the direct DFT (W = 8, oversample
= 128): 3.2e-5 at band 0.5, 8.2e-4 at band 0.75 — the documented
serving tolerance is ``DEGRID_TOLERANCE`` (1e-3) for sky models inside
``band <= 0.75``; see docs/visibility.md for the derivation and the
accuracy table.

Everything here is host-side numpy/scipy, evaluated once per
(support, oversample, band) and cached — the device-facing batch math
lives in `vis.degrid` / `vis.grid`.
"""

from __future__ import annotations

import functools

import numpy as np
import scipy.special

__all__ = [
    "DEGRID_TOLERANCE",
    "MAX_BAND",
    "VisKernel",
    "vis_kernel",
]

# The exactness contract of the visibility path: relative RMS of
# degridded samples against the direct-DFT oracle, for band-limited
# sky models (|x|/N <= MAX_BAND / 2) served with the default kernel.
# Pinned by tests/test_vis.py and asserted by `bench.py --vis --smoke`.
DEGRID_TOLERANCE = 1e-3
MAX_BAND = 0.75

# pro_ang1 chunking, same reliability bound as ops.pswf._CHUNK
_CHUNK = 500


class VisKernel:
    """One precomputed separable degridding kernel.

    :param support: tap count W per axis (even; the taps sit at
        ``floor(u) + d`` for ``d in [-(W/2 - 1), W/2]``)
    :param oversample: tabulated sub-pixel fractions per pixel
    :param band: represented image band as a fraction of N — sources
        outside ``|x| / N <= band / 2`` are outside the fit and carry
        no accuracy guarantee
    """

    def __init__(self, support=8, oversample=128, band=MAX_BAND):
        support = int(support)
        if support < 4 or support % 2:
            raise ValueError(
                f"support must be an even integer >= 4, got {support}"
            )
        if not 0.0 < band <= MAX_BAND:
            raise ValueError(
                f"band must be in (0, {MAX_BAND}], got {band}"
            )
        self.support = support
        self.oversample = int(oversample)
        self.band = float(band)
        self.tolerance = DEGRID_TOLERANCE
        # tap offsets relative to floor(u): patch rows are gathered at
        # u0 + taps, so a sample needs taps[0]..taps[-1] inside its
        # owning subgrid span (vis.mapping enforces it)
        self.taps = np.arange(-(support // 2 - 1), support // 2 + 1)
        self._c = np.pi * support / 2
        self._taper_t, self._taper_w = self._quadrature()
        self.table = self._fit_table()

    # -- PSWF taper -------------------------------------------------

    def _psi(self, x):
        """psi_00 on |x| <= 1, chunked (pro_ang1 misbehaves on large
        fills, see ops.pswf)."""
        x = np.asarray(x, dtype=float)
        out = np.empty_like(x)
        for lo in range(0, x.size, _CHUNK):
            hi = min(lo + _CHUNK, x.size)
            out[lo:hi] = scipy.special.pro_ang1(
                0, 0, self._c, x[lo:hi]
            )[0]
        return out

    def _quadrature(self, n=1024):
        """Midpoint quadrature nodes/weights of psi(2t/W) over
        |t| <= W/2 — the taper integrand."""
        half = self.support / 2
        dt = self.support / n
        t = -half + dt * (np.arange(n) + 0.5)
        w = self._psi(t / half) * dt
        return t, w

    def taper(self, xi):
        """Continuous Fourier transform of the window at image
        coordinate(s) ``xi = x / N`` (real and even — psi is even)."""
        xi = np.asarray(xi, dtype=float)
        out = (
            np.cos(2 * np.pi * xi.reshape(-1, 1) * self._taper_t)
            @ self._taper_w
        ).reshape(xi.shape)
        return float(out) if xi.ndim == 0 else out

    # -- weight table -----------------------------------------------

    def _fit_table(self):
        """[oversample + 1, support] least-squares weights, one row per
        tabulated fraction f = i / oversample (row oversample = f -> 1
        duplicates f -> 0 shifted one pixel; kept so the linear lookup
        never wraps)."""
        n_xi = 4 * self.support + 1
        xi = np.linspace(-self.band / 2, self.band / 2, n_xi)
        tap_phase = np.exp(2j * np.pi * np.outer(xi, self.taps))
        A = np.concatenate([tap_phase.real, tap_phase.imag])
        taper = self.taper(xi)
        table = np.empty(
            (self.oversample + 1, self.support), dtype=float
        )
        for i in range(self.oversample + 1):
            f = i / self.oversample
            b_c = taper * np.exp(2j * np.pi * f * xi)
            b = np.concatenate([b_c.real, b_c.imag])
            table[i] = np.linalg.lstsq(A, b, rcond=None)[0]
        return table

    def weights(self, frac, dtype=np.float32):
        """Per-sample tap weights by linear interpolation of the
        oversampled table.

        :param frac: [B] sub-pixel fractions in [0, 1)
        :return: [B, support] weights, ``dtype``
        """
        frac = np.asarray(frac, dtype=float)
        a = np.clip(frac, 0.0, np.nextafter(1.0, 0.0)) * self.oversample
        i0 = a.astype(int)
        t = (a - i0)[:, None]
        return (
            self.table[i0] * (1.0 - t) + self.table[i0 + 1] * t
        ).astype(dtype)

    # -- grid correction --------------------------------------------

    def grid_correction(self, x, N):
        """Per-axis image-plane correction divisor at pixel offset(s)
        ``x`` from centre: ``taper(x / N)``."""
        return self.taper(np.asarray(x, dtype=float) / N)

    def correct_sources(self, sources, N):
        """Sky-model sources with the separable grid correction applied
        (intensity divided by ``taper(x/N) * taper(y/N)``) — the image
        the engine should transform so degridded samples approximate
        the TRUE visibilities of the input model.

        :param sources: [(intensity, x, y), ...] centre-relative pixels
        :raises ValueError: when a source lies outside the kernel band
            (no accuracy guarantee exists there — widen ``band`` or
            shrink the model instead of serving silently-wrong samples)
        """
        out = []
        for (w, x, y) in sources:
            if max(abs(x), abs(y)) > self.band * N / 2:
                raise ValueError(
                    f"source at ({x}, {y}) outside the kernel band "
                    f"(|x| <= {self.band * N / 2:.0f} for band "
                    f"{self.band} at N={N})"
                )
            out.append(
                (
                    w
                    / (
                        self.grid_correction(x, N)
                        * self.grid_correction(y, N)
                    ),
                    x,
                    y,
                )
            )
        return out

    def as_dict(self):
        """Artifact-block stamp (`bench.py --vis`)."""
        return {
            "support": self.support,
            "oversample": self.oversample,
            "band": self.band,
            "tolerance": self.tolerance,
        }

    def __repr__(self):
        return (
            f"VisKernel(support={self.support}, "
            f"oversample={self.oversample}, band={self.band})"
        )


@functools.lru_cache(maxsize=8)
def vis_kernel(support=8, oversample=128, band=MAX_BAND):
    """Cached `VisKernel` — the table fit costs ~0.1 s of scipy/lstsq
    per (support, oversample, band), paid once per process."""
    return VisKernel(support, oversample, band)
