"""Gridding: the exact adjoint of `vis.degrid`, feeding the backward.

``grid_batch`` scatter-adds each weighted visibility into its
``support x support`` patch — the transpose of the degrid gather with
the SAME indices and the SAME real weights, so the dot-product identity

    < degrid(G), y >  ==  < G, grid(y) >

holds to float accumulation order (pinned by tests/test_vis.py).

`VisGridder` is the streaming accumulator on top: visibility batches
accumulate into per-subgrid planes, version-pinned against the serving
stream (a facet update moves the stream version and the gridder REFUSES
further batches — gridding v-era samples into a v+1 image would corrupt
the update, the same stale-read rule `parallel.streamed
.CachedColumnFeed` enforces on reads). ``emit()`` hands the accumulated
columns over in `StreamedBackward.add_subgrid_group` form — subgrid
columns stacked ``[G, S, xA, xA(, 2)]`` — so gridded visibilities are an
ingest source for the backward/delta path with no adapter in between.
"""

from __future__ import annotations

import functools

import numpy as np

from ..obs import metrics as _metrics

__all__ = ["ADJOINT_TOLERANCE", "VisGridder", "grid_batch"]

# Bound on | <degrid(G), y> - <G, grid(y)> | / |<degrid(G), y>| — the
# dot-product identity holds exactly in exact arithmetic; float32
# accumulation (the engine's serving dtype, x64 stays off) leaves
# reordering noise that cancellation in the batched dot products can
# inflate to ~1e-5, so 1e-4 still catches a real adjoint bug (those
# miss by O(1)) while never flaking on rounding.
ADJOINT_TOLERANCE = 1e-4


@functools.lru_cache(maxsize=None)
def _grid_fn(support):
    import jax
    import jax.numpy as jnp

    offs = jnp.arange(support)

    def body(acc_r, acc_i, iu0, iv0, cu, cv, yr, yi):
        iu = iu0[:, None] + offs
        iv = iv0[:, None] + offs
        w2 = cu[:, :, None] * cv[:, None, :]  # [B, W, W]
        idx = (iu[:, :, None], iv[:, None, :])
        acc_r = acc_r.at[idx].add(yr[:, None, None] * w2)
        acc_i = acc_i.at[idx].add(yi[:, None, None] * w2)
        return acc_r, acc_i

    return jax.jit(body)


def grid_batch(size, iu0, iv0, cu, cv, vis, acc=None, dtype=np.float32):
    """Scatter one visibility batch into a [size, size] grid plane pair.

    :param vis: [B] complex visibilities (sample weights fold in here)
    :param acc: optional (real, imag) planes to accumulate into
    :return: (real, imag) float planes — callers view them complex or
        stack them planar as their backend needs
    """
    n = int(np.asarray(iu0).size)
    W = int(cu.shape[1])
    if acc is None:
        acc_r = np.zeros((size, size), dtype=dtype)
        acc_i = np.zeros((size, size), dtype=dtype)
    else:
        acc_r, acc_i = acc
    vis = np.asarray(vis, dtype=complex)
    fn = _grid_fn(W)
    out_r, out_i = fn(
        np.asarray(acc_r),
        np.asarray(acc_i),
        np.asarray(iu0, dtype=np.int32),
        np.asarray(iv0, dtype=np.int32),
        np.asarray(cu, dtype=acc_r.dtype),
        np.asarray(cv, dtype=acc_r.dtype),
        vis.real.astype(dtype),
        vis.imag.astype(dtype),
    )
    return np.asarray(out_r), np.asarray(out_i)


class VisGridder:
    """Version-pinned visibility -> subgrid-column accumulator.

    :param cover_index: `vis.mapping.VisCoverIndex` over the served
        cover (sharing the service's index keeps grid and degrid on the
        same ownership rule)
    :param kernel: `vis.kernel.VisKernel`
    :param stream_version: the facet-stack version these visibilities
        belong to — pin it from `VisibilityService.stream_version` at
        construction
    :param version_of: zero-arg callable returning the CURRENT stream
        version (e.g. ``lambda: service.stream_version``); when it
        moves past the pinned version, `add_batch` raises LookupError
    :param dtype: accumulator real dtype (match the backward core's)
    """

    def __init__(self, cover_index, kernel, stream_version=0,
                 version_of=None, dtype=np.float32):
        self.cover = cover_index
        self.kernel = kernel
        self.stream_version = int(stream_version)
        self._version_of = version_of
        self.dtype = np.dtype(dtype)
        self._acc = {}  # (off0, off1) -> (real, imag) planes
        self.n_gridded = 0
        self.n_shed = 0
        self.batches = 0

    def _gate(self):
        if self._version_of is None:
            return
        current = int(self._version_of())
        if current != self.stream_version:
            raise LookupError(
                f"gridder pinned at stream version "
                f"{self.stream_version} but the serving stream moved "
                f"to {current} (a facet update landed); gridding "
                "stale-era samples would corrupt the updated image — "
                "re-pin a fresh VisGridder"
            )

    def add_batch(self, uv, vis, weights=None):
        """Accumulate one weighted visibility batch.

        :param uv: [B, 2] sample coordinates
        :param vis: [B] complex visibilities
        :param weights: optional [B] real sample weights
        :return: number of samples gridded (outside-cover samples are
            counted in ``n_shed`` and skipped, mirroring the degrid
            shed rule)
        :raises LookupError: when the pinned stream version is stale
        """
        self._gate()
        uv = np.atleast_2d(np.asarray(uv, dtype=float))
        vis = np.asarray(vis, dtype=complex)
        if weights is not None:
            vis = vis * np.asarray(weights, dtype=float)
        owners, shed = self.cover.map_samples(uv)
        self.n_shed += len(shed)
        gridded = 0
        for (off0, off1), entry in owners.items():
            sg = self.cover.config(off0, off1)
            cu = self.kernel.weights(entry["fu"], dtype=self.dtype)
            cv = self.kernel.weights(entry["fv"], dtype=self.dtype)
            acc = self._acc.get((off0, off1))
            B, W = cu.shape
            # attributed exactly as plan.price_vis prices the stage
            # (two scattered planes + the weight outer product), so
            # plan.autotune.refit recovers a measured vis.grid rate
            with _metrics.stage(
                "vis.grid",
                flops=8 * B * W * W,
                bytes_moved=2 * B * W * W * 4,
            ):
                self._acc[(off0, off1)] = grid_batch(
                    sg.size, entry["iu0"], entry["iv0"], cu, cv,
                    vis[entry["idx"]], acc=acc, dtype=self.dtype,
                )
            gridded += len(entry["idx"])
        self.n_gridded += gridded
        self.batches += 1
        return gridded

    def subgrid(self, off0, off1):
        """One accumulated plane pair as a complex array (tests)."""
        acc_r, acc_i = self._acc[(off0, off1)]
        return acc_r + 1j * acc_i

    def emit(self, planar=True):
        """The accumulated columns in `StreamedBackward
        .add_subgrid_group` form.

        :param planar: stack ``[..., 2]`` real/imag planes (the planar
            backward core's layout); False keeps complex rows
        :return: ``(col_sg_lists, subgrids_group)`` — per-column config
            lists (one shared off0 each, trailing rows zero-padded by
            the consumer's contract) and the ``[G, S, size, size(, 2)]``
            stacked array
        """
        if not self._acc:
            raise ValueError("nothing gridded yet")
        cols = {}
        for (off0, off1) in sorted(self._acc):
            cols.setdefault(off0, []).append(off1)
        S = max(len(v) for v in cols.values())
        col_sg_lists, stacks = [], []
        for off0, off1s in cols.items():
            sgs = [self.cover.config(off0, o1) for o1 in off1s]
            col_sg_lists.append(sgs)
            rows = []
            for o1 in off1s:
                acc_r, acc_i = self._acc[(off0, o1)]
                if planar:
                    rows.append(np.stack([acc_r, acc_i], axis=-1))
                else:
                    rows.append(acc_r + 1j * acc_i)
            pad = S - len(rows)
            if pad:
                rows += [np.zeros_like(rows[0])] * pad
            stacks.append(np.stack(rows))
        return col_sg_lists, np.stack(stacks)
