"""Visibility-space serving: degrid/grid as the product surface.

The serving stack answers *subgrid* requests; this package turns those
rows into the quantity interferometry clients actually consume —
visibility samples at arbitrary fractional (u, v) — and back:

* `vis.kernel` — PSWF-derived separable degridding kernel + image-
  plane grid correction (host-side precompute, accuracy contract
  ``DEGRID_TOLERANCE``);
* `vis.mapping` — sample -> owning-subgrid index over the served
  cover (outside-cover samples are shed, never answered wrong);
* `vis.degrid` — the jitted gather + contraction batch body (einsum
  by default, fused Pallas behind ``SWIFTLY_PALLAS``);
* `vis.grid` — the exact adjoint scatter + the version-pinned
  `VisGridder` accumulator feeding
  `parallel.streamed.StreamedBackward.add_subgrid_group`;
* `vis.service` — `VisibilityService`, the product surface: admission
  / coalescing / cache-feed / compute-fallback / facet-update
  version gates, all shared with `serve`;
* `vis.oracle` — direct-DFT reference for accuracy audits.

See docs/visibility.md for the end-to-end story.
"""

from .kernel import DEGRID_TOLERANCE, MAX_BAND, VisKernel, vis_kernel
from .mapping import VisCoverIndex
from .degrid import bucket_size, degrid_batch, split_row_planes
from .grid import ADJOINT_TOLERANCE, VisGridder, grid_batch
from .oracle import corrected_sources, vis_oracle
from .service import (
    FleetRowSource,
    VisHandle,
    VisRequest,
    VisibilityService,
)

__all__ = [
    "ADJOINT_TOLERANCE",
    "DEGRID_TOLERANCE",
    "MAX_BAND",
    "FleetRowSource",
    "VisCoverIndex",
    "VisGridder",
    "VisHandle",
    "VisKernel",
    "VisRequest",
    "VisibilityService",
    "bucket_size",
    "corrected_sources",
    "degrid_batch",
    "grid_batch",
    "split_row_planes",
    "vis_kernel",
    "vis_oracle",
]
