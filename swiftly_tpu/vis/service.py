"""`VisibilityService`: visibility samples as the product surface.

The serving stack so far answers *subgrid* requests (`serve.service`);
radio-astronomy clients want *visibilities* — the sky transform sampled
at arbitrary fractional (u, v) baselines. This service closes the gap:
a submitted sample batch is split by owning subgrid
(`vis.mapping.VisCoverIndex`), admitted into the SAME
`serve.queue.AdmissionQueue` / `serve.scheduler.CoalescingScheduler`
machinery (coalesced by owning column, power-of-two sample buckets),
and answered by ONE degrid dispatch per touched subgrid
(`vis.degrid.degrid_batch`) off a row obtained through the serving
ladder:

1. **cache feed** — `parallel.streamed.CachedColumnFeed.lookup` (one
   host-RAM row read, version-gated: a feed recorded at a superseded
   stream version raises and the request falls through);
2. **compute fallback** — ``row_source(config)`` when given (e.g.
   `FleetRowSource` routing through a `serve.fleet.ServeFleet`, so
   failover/brownout/hedging apply to visibility serving unchanged),
   else `SwiftlyForward.get_subgrid_task` on the wrapped forward.

The same jitted degrid body runs on cache-fed and computed rows, so
the serve tier's cache-vs-compute bit-identity carries through to
samples (pinned by tests/test_vis.py).

Version discipline is PR-11's: requests are stamped with the stream
version at submit; `post_facet_update` drains the queue, swaps the
forward/feed, and bumps the version, so a facet update can never serve
a stale sample — stale-stamped stragglers are version-fallback'd onto
the (new) compute path, and a `vis.grid.VisGridder` pinned to the old
version refuses further batches outright.

Samples whose kernel footprint straddles a subgrid boundary (or falls
off the cover) are SHED with ``shed_reason="outside_cover"`` — a
structured refusal, never a silently-wrong answer.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..serve.queue import (
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_SHED,
    AdmissionQueue,
    RequestResult,
    SubgridRequest,
)
from ..serve.scheduler import CoalescingScheduler
from .degrid import degrid_batch
from .kernel import vis_kernel
from .mapping import VisCoverIndex

__all__ = ["FleetRowSource", "VisHandle", "VisRequest",
           "VisibilityService"]

_LATENCY_RING = 65536


def _quantile(sorted_samples, q):
    if not sorted_samples:
        return 0.0
    i = min(len(sorted_samples) - 1, int(q * len(sorted_samples)))
    return sorted_samples[i]


class VisRequest(SubgridRequest):
    """One owning-subgrid slice of a submitted sample batch.

    The admission/scheduling machinery sees a `SubgridRequest` (it
    keys on ``.config.off0``); the extra slots carry the slice's
    sample geometry and the parent handle to report into.
    """

    __slots__ = ("idx", "iu0", "iv0", "fu", "fv", "parent")

    def __init__(self, config, idx, iu0, iv0, fu, fv, parent,
                 priority=0, deadline_s=None):
        super().__init__(config, priority=priority,
                         deadline_s=deadline_s)
        self.idx = idx
        self.iu0 = iu0
        self.iv0 = iv0
        self.fu = fu
        self.fv = fv
        self.parent = parent

    @property
    def n_samples(self):
        return int(self.idx.size)


class VisHandle:
    """Completion handle for one submitted (u, v) batch.

    ``data`` is the [B] complex128 sample vector, NaN at positions that
    were shed or failed; ``status`` aggregates the per-subgrid slices:
    ``"ok"`` (every sample served), ``"shed"`` (every sample shed —
    ``shed_reason`` says why, e.g. ``outside_cover``), or ``"partial"``
    (mixed; ``shed_idx`` lists the unanswered positions).
    """

    def __init__(self, n_samples, submit_t):
        self.n_samples = int(n_samples)
        self.submit_t = submit_t
        self.data = np.full(self.n_samples, np.nan + 0j,
                            dtype=np.complex128)
        self.shed_idx = []
        self.shed_reason = None
        self.children = []
        self.latency_s = 0.0
        self._served = 0
        self._pending = 0
        self._event = threading.Event()

    @property
    def status(self):
        if self._served == self.n_samples:
            return STATUS_OK
        if self._served == 0:
            return STATUS_SHED
        return "partial"

    @property
    def done(self):
        return self._event.is_set()

    def wait(self, timeout=None):
        self._event.wait(timeout)
        return self

    def _shed(self, idx, reason):
        self.shed_idx.extend(int(i) for i in np.atleast_1d(idx))
        if self.shed_reason is None:
            self.shed_reason = reason

    def _child_done(self, req, result):
        if result.status == STATUS_OK:
            self.data[req.idx] = result.data
            self._served += req.n_samples
        else:
            self._shed(req.idx, result.shed_reason or result.status)
        self._pending -= 1
        if self._pending <= 0:
            self.latency_s = max(
                (r.result.latency_s for r in self.children
                 if r.result is not None),
                default=0.0,
            )
            self._event.set()

    def __repr__(self):
        return (
            f"<VisHandle n={self.n_samples} status={self.status} "
            f"served={self._served} shed={len(self.shed_idx)}>"
        )


class FleetRowSource:
    """Row fetch routed through a `serve.fleet.ServeFleet`.

    Passing one of these as ``row_source=`` puts the fleet's whole
    resilience ladder — rendezvous routing, failover, brownout,
    hedged retries — under visibility serving without either side
    changing: the vis service just sees rows, the fleet just sees
    subgrid requests.
    """

    def __init__(self, fleet, priority=0, deadline_s=None):
        self.fleet = fleet
        self.priority = priority
        self.deadline_s = deadline_s

    def __call__(self, config):
        req = self.fleet.submit(config, priority=self.priority,
                                deadline_s=self.deadline_s)
        # FleetRequest.wait returns the RequestResult (None on wait
        # timeout), unlike SubgridRequest.wait which returns itself
        result = req.wait(timeout=self.deadline_s)
        if result is None or not result.ok:
            status = getattr(result, "status", None)
            raise RuntimeError(
                f"fleet row fetch for column {config.off0} failed: "
                f"{status}"
            )
        return np.asarray(result.data)


class VisibilityService:
    """Serve visibility sample batches over a prepared forward.

    :param fwd: prepared `SwiftlyForward` (compute fallback + the LRU
        whose resident columns steer the scheduler's locality
        preference); may be None when ``row_source`` is given
    :param subgrid_configs: the served cover (`models.covers
        .make_full_subgrid_cover` or any SubgridConfig list)
    :param N: grid period; defaults to ``fwd.config.image_size``
    :param kernel: `vis.kernel.VisKernel` (default: the cached
        default kernel)
    :param cache_feed: optional `parallel.streamed.CachedColumnFeed`
        (rung 1 of the row ladder)
    :param row_source: optional ``fn(config) -> row`` compute fallback
        (e.g. `FleetRowSource`); default is
        ``fwd.get_subgrid_task``
    :param queue: `serve.queue.AdmissionQueue` (default depth
        ``max_depth``)
    :param scheduler: `serve.scheduler.CoalescingScheduler`
    :param timeout_s: service-wide per-request deadline
    :param slo_ms: per-request latency SLO for ``stats()``
    :param hbm_budget_bytes: optional projected-device-cost admission
        cap, priced with the plan compiler's serve byte projections
        (`plan.model.projected_request_bytes`) — past it, slices shed
        with the queue's structured cost reason
    """

    def __init__(self, fwd=None, subgrid_configs=None, N=None,
                 kernel=None, cache_feed=None, row_source=None,
                 queue=None, scheduler=None, timeout_s=None,
                 slo_ms=None, max_depth=512, hbm_budget_bytes=None):
        if subgrid_configs is None:
            raise ValueError("need the served subgrid cover")
        if fwd is None and row_source is None:
            raise ValueError("need a forward or a row_source")
        if N is None:
            N = getattr(getattr(fwd, "config", None),
                        "image_size", None)
        if N is None:
            raise ValueError(
                "need N (or a forward whose config carries image_size)"
            )
        self.fwd = fwd
        self.kernel = kernel or vis_kernel()
        self.cover = VisCoverIndex(
            subgrid_configs, self.kernel.support, int(N)
        )
        self.cache_feed = cache_feed
        self.row_source = row_source
        self.stream_version = int(
            getattr(cache_feed, "stream_version", 0)
        )
        if queue is None:
            # admission byte model: a pending vis slice pins one
            # served row (the subgrid it degrids off) plus the column
            # intermediates a compute fallback materialises — the same
            # plan-priced projections the subgrid service sheds by
            request_bytes = column_bytes = 0
            if hbm_budget_bytes is not None and fwd is not None:
                from ..plan.model import (
                    projected_column_bytes,
                    projected_request_bytes,
                )

                request_bytes = projected_request_bytes(fwd.config)
                column_bytes = projected_column_bytes(fwd)
            queue = AdmissionQueue(
                max_depth=max_depth,
                hbm_budget_bytes=hbm_budget_bytes,
                request_bytes=request_bytes,
                column_bytes=column_bytes,
            )
        self.queue = queue
        self.scheduler = scheduler or CoalescingScheduler()
        self.timeout_s = timeout_s
        self.slo_ms = slo_ms
        self._counts = {
            "requests": 0, "samples": 0, "served": 0,
            "served_samples": 0, "shed": 0, "shed_samples": 0,
            "expired": 0, "batches": 0, "coalesced": 0,
            "cache_hits": 0, "cache_fallbacks": 0,
            "version_fallbacks": 0, "slo_violations": 0,
            "facet_updates": 0,
        }
        self._shed_reasons = {}
        self._latencies = []
        self._lat_i = 0
        self._journeys = []
        self._jour_i = 0
        self._pump_lock = threading.Lock()

    # -- submission ---------------------------------------------------

    def submit(self, uv, priority=0, deadline_s=None):
        """Admit one sample batch; returns a `VisHandle`.

        Outside-cover samples are shed immediately (structured,
        per-sample); the rest are split into one `VisRequest` per
        owning subgrid and admitted. Admission never blocks — a queue
        rejection sheds that slice with the queue's reason.
        """
        if deadline_s is None:
            deadline_s = self.timeout_s
        elif self.timeout_s is not None:
            deadline_s = min(deadline_s, self.timeout_s)
        uv = np.atleast_2d(np.asarray(uv, dtype=float))
        handle = VisHandle(uv.shape[0], time.perf_counter())
        self._counts["requests"] += 1
        self._counts["samples"] += handle.n_samples
        _metrics.count("vis.requests")
        _metrics.count("vis.samples", handle.n_samples)
        owners, shed = self.cover.map_samples(uv)
        if shed:
            self._shed_samples(handle, shed, "outside_cover")
        for (off0, off1), entry in owners.items():
            req = VisRequest(
                self.cover.config(off0, off1), entry["idx"],
                entry["iu0"], entry["iv0"], entry["fu"], entry["fv"],
                handle, priority=priority, deadline_s=deadline_s,
            )
            req.stream_version = self.stream_version
            handle.children.append(req)
            handle._pending += 1
            admitted, reason = self.queue.offer(req)
            if not admitted:
                self._shed_counts(req.n_samples, reason)
                req._complete(RequestResult(
                    STATUS_SHED, shed_reason=reason,
                    retry_after_s=self.queue.retry_after_hint(),
                ))
                handle._child_done(req, req.result)
        _metrics.gauge_max("vis.queue_depth_peak", len(self.queue))
        if handle._pending == 0:
            handle._event.set()
        return handle

    def _shed_counts(self, n_samples, reason):
        self._counts["shed"] += 1
        self._counts["shed_samples"] += n_samples
        self._shed_reasons[reason] = (
            self._shed_reasons.get(reason, 0) + n_samples
        )
        _metrics.count("vis.shed")
        _metrics.count(f"vis.shed.{reason}", n_samples)

    def _shed_samples(self, handle, idx, reason):
        self._shed_counts(len(idx), reason)
        _trace.instant("vis.shed", cat="vis", reason=reason,
                       n_samples=len(idx))
        handle._shed(idx, reason)

    def serve(self, uv, priority=0, deadline_s=None):
        """Submit one batch and pump until it completes (sync use)."""
        handle = self.submit(uv, priority=priority,
                             deadline_s=deadline_s)
        while not handle.done:
            if not self.pump_once():
                break
        return handle

    # -- pump ---------------------------------------------------------

    def pump_once(self, now=None):
        """One scheduling cycle; returns requests completed."""
        with self._pump_lock:
            return self._pump_locked(now)

    def _pump_locked(self, now):
        now = time.perf_counter() if now is None else now
        n_done = 0
        for req in self.queue.take_expired(now):
            self._counts["expired"] += 1
            _metrics.count("vis.expired")
            self._finish(
                req, RequestResult(STATUS_EXPIRED, error="deadline")
            )
            n_done += 1
        summaries = self.queue.columns()
        if not summaries:
            return n_done
        hot = (
            set(self.fwd.lru.keys())
            if self.fwd is not None and hasattr(self.fwd, "lru")
            else set()
        )
        off0 = self.scheduler.pick_column(summaries, hot, now)
        if off0 is None:
            return n_done
        reqs = self.queue.take(
            off0, limit=self.scheduler.max_batch, now=now
        )
        groups = {}
        for req in reqs:
            key = (req.config.off0, req.config.off1)
            groups.setdefault(key, []).append(req)
        for rs in groups.values():
            self._serve_subgrid(rs)
            n_done += len(rs)
        return n_done

    def _fetch_row(self, sg, reqs):
        """The row ladder: version-gated cache feed, then compute."""
        row_bytes = 2 * sg.size * sg.size * 4
        if self.cache_feed is not None:
            stale = sum(
                1 for r in reqs
                if r.stream_version != self.stream_version
            )
            if stale:
                # admitted under a superseded facet stack: the feed's
                # rows no longer match the request's era — fall
                # through to compute against the CURRENT stack
                # (fresher than asked; never staler)
                self._counts["version_fallbacks"] += stale
                _metrics.count("vis.version_fallbacks", stale)
            else:
                try:
                    with _metrics.stage("vis.row_fetch",
                                        bytes_moved=row_bytes):
                        row = self.cache_feed.lookup(sg)
                except LookupError:
                    self._counts["cache_fallbacks"] += 1
                    _metrics.count("vis.cache_fallbacks")
                    row = None
                if row is not None:
                    self._counts["cache_hits"] += 1
                    _metrics.count("vis.cache_hits")
                    return row, "cache"
        with _metrics.stage("vis.row_fetch", bytes_moved=row_bytes):
            if self.row_source is not None:
                row = self.row_source(sg)
            else:
                row = np.asarray(self.fwd.get_subgrid_task(sg))
        return row, "compute"

    def _serve_subgrid(self, reqs):
        """Answer every sample of one subgrid in one degrid dispatch."""
        sg = reqs[0].config
        try:
            row, path = self._fetch_row(sg, reqs)
        except Exception as exc:  # row ladder exhausted
            for req in reqs:
                self._shed_counts(req.n_samples, "row_fetch_failed")
                self._finish(req, RequestResult(
                    STATUS_SHED, shed_reason="row_fetch_failed",
                    error=repr(exc),
                ))
            return
        iu0 = np.concatenate([r.iu0 for r in reqs])
        iv0 = np.concatenate([r.iv0 for r in reqs])
        fu = np.concatenate([r.fu for r in reqs])
        fv = np.concatenate([r.fv for r in reqs])
        cu = self.kernel.weights(fu, dtype=np.float64)
        cv = self.kernel.weights(fv, dtype=np.float64)
        B, W = cu.shape
        with _metrics.stage(
            "vis.degrid",
            flops=6 * B * W * W,
            bytes_moved=2 * B * W * W * 4,
        ):
            vis = degrid_batch(row, iu0, iv0, cu, cv)
        now = time.perf_counter()
        lo = 0
        for req in reqs:
            req.compute_t = now
            n = req.n_samples
            self._counts["coalesced"] += 1 if len(reqs) > 1 else 0
            self._counts["served_samples"] += n
            _metrics.count("vis.served_samples", n)
            self._finish(req, RequestResult(
                STATUS_OK, data=vis[lo:lo + n], path=path,
                batch_size=B, coalesced=len(reqs),
            ))
            lo += n
        self._counts["batches"] += 1

    def _finish(self, req, result):
        now = time.perf_counter()
        result.latency_s = now - req.submit_t
        if result.status == STATUS_OK:
            self._counts["served"] += 1
            _metrics.observe("vis.request", result.latency_s)
            if req.take_t and req.compute_t:
                result.journey = {
                    "queue_s": req.take_t - req.submit_t,
                    "compute_s": req.compute_t - req.take_t,
                    "transfer_s": now - req.compute_t,
                }
                if len(self._journeys) < _LATENCY_RING:
                    self._journeys.append(result.journey)
                else:
                    self._journeys[self._jour_i] = result.journey
                    self._jour_i = (self._jour_i + 1) % _LATENCY_RING
            if len(self._latencies) < _LATENCY_RING:
                self._latencies.append(result.latency_s)
            else:
                self._latencies[self._lat_i] = result.latency_s
                self._lat_i = (self._lat_i + 1) % _LATENCY_RING
            if (
                self.slo_ms is not None
                and result.latency_s * 1e3 > self.slo_ms
            ):
                self._counts["slo_violations"] += 1
                _metrics.count("vis.slo_violations")
        req._complete(result)
        if req.parent is not None:
            req.parent._child_done(req, result)

    # -- incremental facet updates ------------------------------------

    def post_facet_update(self, fwd=None, cache_feed=None,
                          stream_version=None):
        """Adopt an updated facet stack: drain, swap, bump.

        In-flight requests complete at their admitted version BEFORE
        the swap; requests submitted after this returns carry the new
        version. A straggler stamped with the old version that arrives
        at the feed later is version-fallback'd onto the (new) compute
        path — a facet update can never serve a stale sample. With no
        replacement ``cache_feed`` the old feed is DROPPED (its rows
        are the superseded era's) and the compute path serves until a
        re-recorded feed is adopted.
        """
        while self.pump_once():
            pass
        with self._pump_lock:
            if fwd is not None:
                self.fwd = fwd
            # swap or DROP the feed: with no replacement, the old
            # feed's rows belong to the superseded era — keeping them
            # would serve stale samples to new-version requests, the
            # exact hole the version discipline exists to close
            self.cache_feed = cache_feed
            if stream_version is None:
                stream_version = self.stream_version + 1
            self.stream_version = int(stream_version)
            self._counts["facet_updates"] += 1
            _metrics.count("vis.facet_updates")
            _trace.instant("vis.facet_update", cat="vis",
                           stream_version=self.stream_version)
        return self.stream_version

    # -- SLO export ---------------------------------------------------

    def stats(self):
        """JSON-ready serving metrics (the ``bench.py --vis``
        artifact block): request/sample counts, shed/coalesce/cache
        rates, latency quantiles in ms, SLO attainment."""
        c = dict(self._counts)
        lat = sorted(self._latencies)
        served = c["served"]
        requests = c["requests"]
        samples = c["samples"]
        out = {
            "n_requests": requests,
            "n_samples": samples,
            "n_served": served,
            "n_served_samples": c["served_samples"],
            "n_shed": c["shed"],
            "n_shed_samples": c["shed_samples"],
            "n_expired": c["expired"],
            "n_batches": c["batches"],
            "cache_hits": c["cache_hits"],
            "cache_fallbacks": c["cache_fallbacks"],
            "stream_version": self.stream_version,
            "facet_updates": c["facet_updates"],
            "version_fallbacks": c["version_fallbacks"],
            "shed_rate": (
                round(c["shed_samples"] / samples, 4) if samples
                else 0.0
            ),
            "shed_reasons": dict(self._shed_reasons),
            "coalesce_hit_rate": (
                round(c["coalesced"] / served, 4) if served else 0.0
            ),
            "mean_batch": (
                round(c["served_samples"] / c["batches"], 2)
                if c["batches"] else 0.0
            ),
            "p50_ms": round(_quantile(lat, 0.50) * 1e3, 3),
            "p99_ms": round(_quantile(lat, 0.99) * 1e3, 3),
            "max_ms": round((lat[-1] if lat else 0.0) * 1e3, 3),
            "journey": self._journey_stats(),
        }
        if self.slo_ms is not None:
            out["slo_ms"] = self.slo_ms
            out["slo_violations"] = c["slo_violations"]
            out["slo_attainment"] = (
                round(1.0 - c["slo_violations"] / served, 4)
                if served else 1.0
            )
        return out

    def _journey_stats(self):
        if not self._journeys:
            return None
        total = sum(
            j["queue_s"] + j["compute_s"] + j["transfer_s"]
            for j in self._journeys
        )
        out = {"n": len(self._journeys)}
        for seg in ("queue_s", "compute_s", "transfer_s"):
            vals = sorted(j[seg] for j in self._journeys)
            seg_total = sum(vals)
            out[seg[:-2]] = {
                "p50_ms": round(_quantile(vals, 0.50) * 1e3, 3),
                "p99_ms": round(_quantile(vals, 0.99) * 1e3, 3),
                "total_s": round(seg_total, 6),
                "share": round(seg_total / total, 4) if total else 0.0,
            }
        return out
