"""Direct-DFT visibility oracle — the accuracy reference.

The grid convention is `ops.oracle.make_subgrid_from_sources` extended
off the integer lattice: a subgrid pixel at integer (u, v) is

    G[u, v] = (1/N^2) * sum_s I_s * exp(+2 pi i (u x_s + v y_s) / N)

so the continuous visibility at arbitrary (u, v) is the same sum with
fractional coordinates. `vis_oracle` evaluates it directly (O(B * S),
smoke-scale only) and is what `bench.py --vis --smoke` and
tests/test_vis.py audit degridded samples against.

`corrected_sources` re-exports the kernel's grid correction: the sky
model the ENGINE should transform (facets built from the corrected
sources) so that degrid output approximates the TRUE visibilities of
the uncorrected model — see docs/visibility.md for why the correction
lives in image space.
"""

from __future__ import annotations

import numpy as np

__all__ = ["corrected_sources", "vis_oracle"]


def vis_oracle(sources, uv, N):
    """Direct-DFT visibilities of a point-source sky model.

    :param sources: [(intensity, x, y), ...] centre-relative pixels
        (the `ops.oracle` source convention)
    :param uv: [B, 2] fractional grid coordinates
    :param N: image/grid size
    :return: [B] complex128 visibilities
    """
    uv = np.atleast_2d(np.asarray(uv, dtype=float))
    out = np.zeros(uv.shape[0], dtype=complex)
    for (w, x, y) in sources:
        out += (w / N**2) * np.exp(
            2j * np.pi * (uv[:, 0] * x + uv[:, 1] * y) / N
        )
    return out


def corrected_sources(kernel, sources, N):
    """Grid-corrected sky model for serving through ``kernel`` —
    `vis.kernel.VisKernel.correct_sources` under its bench/test name."""
    return kernel.correct_sources(sources, N)
