"""Subgrid-stream spill cache: persist a streamed forward's output once,
feed every backward consume pass from the cache.

A facet-partitioned sampled backward (bench.py's ``roundtrip-streamed``
at 64k+) runs P passes over facet subsets, and each pass needs the SAME
subgrid stream — before this cache the forward replayed P times (at 64k:
8 × ~73 s of replay in a 703 s round trip, the headline defect of the
round-5 ledger). The cache is the offload-and-overlap discipline of
"Large-Scale Discrete Fourier Transform on TPUs" (arXiv:2002.03260)
applied to the stream: during the single forward pass each column
group's finished subgrid stack is copied device→host one group behind
the compute (the d2h overlaps the next group's dispatch chain), and
during each backward consume pass the stacks are uploaded host→device
one group AHEAD of the consumer (double-buffered prefetch), so the MXU
never waits on the wire.

Storage is a host-RAM ring with optional disk backing:

* entries up to ``SWIFTLY_SPILL_BUDGET_GB`` (default: half of
  ``MemAvailable``) stay in RAM;
* past the budget, entries spill to ``SWIFTLY_SPILL_DIR`` as ``.npy``
  memmaps, written in bounded chunks (no multi-GiB dirty-page bursts);
* with no disk dir, over-budget entries are EVICTED: the fill is marked
  incomplete (``gave_up``) and consumers fall back to replaying the
  forward — a capacity miss degrades to the old cost model, never to a
  wrong answer.

The cache stores plain float arrays; a d2h→h2d round trip of those is
bit-exact, so a cache-fed backward is bit-identical to a replay-fed one
(pinned by tests/test_spill.py). Instrumentation: ``spill.write`` /
``spill.read`` / ``spill.h2d`` stage timers (bytes_moved attributed) and
``spill.writes`` / ``spill.evictions`` / ``spill.prefetch_hits`` /
``spill.disk_reads`` / ``spill.fallback_replays`` counters, recorded by
the streamed executor against ``obs.metrics``.
"""

from __future__ import annotations

import contextlib
import glob
import logging
import os
import shutil
import tempfile
import threading
import time

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..resilience import degrade as _degrade
from ..resilience.faults import fault_point
from ..resilience.retry import retry_transient

__all__ = ["SpillCache", "StreamMidPatch", "spill_budget_bytes"]

logger = logging.getLogger(__name__)

# begin_patch waits this long for in-flight readers to drain before
# proceeding anyway (readers are single-row copies; a wait this long
# means a reader thread died mid-read — blocking the patch forever
# would wedge the whole update path behind a corpse)
_PATCH_DRAIN_TIMEOUT_S = 5.0


class StreamMidPatch(LookupError):
    """A row read raced ``begin_patch``: the stream is mid-rewrite.

    A LookupError subclass so serving-path consumers
    (`parallel.streamed.CachedColumnFeed`, `serve.SubgridService`)
    treat it exactly like a stale-feed bounce — fall back to compute,
    retry once the patch window closes."""

# chunk size for disk-backed writes: bounds the per-write dirty-page
# burst while keeping the stream sequential (memmap-friendly)
_DISK_CHUNK_BYTES = 256e6


def spill_budget_bytes():
    """Host-RAM byte budget for spilled stream entries.

    ``SWIFTLY_SPILL_BUDGET_GB`` when set; else half of the kernel's
    ``MemAvailable`` at call time (the stream shares the host with the
    facet data and staging buffers); else a conservative 8 GiB.
    """
    env = os.environ.get("SWIFTLY_SPILL_BUDGET_GB")
    if env:
        return float(env) * 2**30
    try:
        with open("/proc/meminfo") as fh:
            for line in fh:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024 / 2
    except Exception:  # pragma: no cover - non-linux
        pass
    return 8 * 2**30  # pragma: no cover - /proc always present on CI


class SpillCache:
    """Ordered store of one forward pass's column-group subgrid stacks.

    Lifecycle: ``begin_fill()`` → ``put(meta, array)`` per group →
    ``end_fill()``; then ``complete`` is True iff every put landed (RAM
    or disk). Consumers iterate ``range(len(cache))`` with ``meta(k)`` /
    ``get(k)``. ``reset()`` returns to empty (deleting disk files).

    :param budget_bytes: host-RAM budget (default `spill_budget_bytes`)
    :param spill_dir: directory for over-budget entries; default
        ``SWIFTLY_SPILL_DIR``; None disables disk backing (over-budget
        entries are evicted and the fill gives up)
    :param policy: the compiled plan's spill-policy dict
        (`plan.SpillPolicy.as_dict`) when this cache was budgeted by
        `compile_plan` — recorded verbatim in `stats()` so artifacts
        show which plan priced the cache (None for self-budgeted use)
    """

    def __init__(self, budget_bytes=None, spill_dir=None, policy=None):
        self.budget_bytes = (
            spill_budget_bytes() if budget_bytes is None else float(budget_bytes)
        )
        if spill_dir is None:
            spill_dir = os.environ.get("SWIFTLY_SPILL_DIR") or None
        self.spill_dir = spill_dir
        self.policy = dict(policy) if policy else None
        self._own_dir = None  # created lazily under spill_dir
        self._entries = []  # ("ram", ndarray) | ("disk", path)
        self._meta = []
        self.ram_bytes = 0
        self.disk_bytes = 0
        self.complete = False
        self.gave_up = False
        self.tag = None  # stream identity (set by begin_fill)
        # monotone facet-stack version (stamped by
        # `delta.FacetDeltaLedger`); 0 = unversioned. Consumers that
        # captured a version (`parallel.streamed.CachedColumnFeed`)
        # refuse rows once it moves — a patched stream can never serve
        # through a feed indexed before the patch.
        self.stream_version = 0
        # True while a patcher rewrites entries (begin_patch/end_patch);
        # feeds refuse lookups for the whole window, so a concurrent
        # reader can never observe a partially-patched stream
        self.patching = False
        # concurrency: one lock guards entry/meta/counter mutation; the
        # condition implements the reader–writer gate (`begin_patch`
        # drains in-flight row reads before the rewrite starts, and new
        # reads bounce with `StreamMidPatch` until `end_patch`). The
        # patcher's own thread passes the gate — `patch_entry` reads the
        # base entry inside the window it opened.
        self._lock = threading.RLock()
        self._readers = threading.Condition(self._lock)
        self._active_readers = 0
        self._patcher_tid = None
        self.counters = {
            "writes": 0,
            "evictions": 0,
            "ram_reads": 0,
            "disk_reads": 0,
            "fills": 0,
            "patches": 0,
            "exported_entries": 0,
        }

    # -- concurrency --------------------------------------------------------

    def _bump(self, name, n=1):
        """Thread-safe counter increment (the fabric's concurrent
        readers would otherwise lose updates to the plain ``+=``)."""
        with self._lock:
            self.counters[name] += n

    @contextlib.contextmanager
    def _read_gate(self):
        """Row-read side of the reader–writer gate: registers the read
        so `begin_patch` can drain it, and bounces reads that arrive
        inside a patch window (`StreamMidPatch` — unless the reader IS
        the patcher, which must read base entries mid-window)."""
        me = threading.get_ident()
        with self._readers:
            if self.patching and me != self._patcher_tid:
                raise StreamMidPatch(
                    "stream is mid-patch (begin_patch/end_patch window); "
                    "fall back to compute and retry after the update"
                )
            self._active_readers += 1
        try:
            yield
        finally:
            with self._readers:
                self._active_readers -= 1
                if self._active_readers == 0:
                    self._readers.notify_all()

    # -- fill ---------------------------------------------------------------

    def begin_fill(self, tag=None):
        """Start (re)recording a stream; drops any previous entries and
        sweeps orphaned ``.tmp`` files a crashed fill may have left.
        ``tag`` identifies the stream (e.g. the cover's shape) so a
        consumer can refuse a cache recorded for different inputs."""
        self._clear_entries()
        self._sweep_orphans()
        with self._lock:
            self.complete = False
            self.gave_up = False
            self.tag = tag
            self.counters["fills"] += 1
        _trace.instant("spill.begin_fill", cat="spill", tag=str(tag))

    def put(self, meta, array) -> bool:
        """Append one group's host array (+ its per-column metadata).

        Returns False when the entry was evicted (over budget, no disk
        backing) — the fill is then marked ``gave_up`` and ``end_fill``
        will leave the cache incomplete.
        """
        array = np.asarray(array)
        self._bump("writes")
        if self.ram_bytes + array.nbytes <= self.budget_bytes:
            with self._lock:
                self._entries.append(("ram", array))
                self.ram_bytes += array.nbytes
        elif self.spill_dir is not None:
            try:
                path = self._disk_write(len(self._entries), array)
            except Exception as exc:
                # degradation ladder rung 1: the spill disk failed past
                # its retries — drop to a host-RAM-only cache for the
                # rest of the run (this over-budget entry evicts, so the
                # fill gives up and consumers degrade to forward replay:
                # slower, never wrong)
                logger.warning(
                    "spill disk write failed (%s: %s); degrading to "
                    "host-RAM-only cache — backward passes will fall "
                    "back to forward replay",
                    type(exc).__name__, exc,
                )
                _degrade.record(
                    "spill", "disk_to_ram",
                    f"{type(exc).__name__}: {exc}",
                )
                self.spill_dir = None
                self._bump("evictions")
                self.gave_up = True
                _metrics.count("spill.evictions")
                return False
            with self._lock:
                self._entries.append(("disk", path))
                self.disk_bytes += array.nbytes
        else:
            self._bump("evictions")
            self.gave_up = True
            _metrics.count("spill.evictions")
            _trace.instant("spill.evict", cat="spill",
                           entry=len(self._entries),
                           nbytes=int(array.nbytes))
            return False
        with self._lock:
            self._meta.append(meta)
        return True

    def end_fill(self):
        """Seal the fill: the cache is complete iff nothing was evicted
        and at least one entry landed."""
        self.complete = bool(self._entries) and not self.gave_up
        _trace.instant(
            "spill.end_fill", cat="spill", entries=len(self._entries),
            complete=self.complete, ram_bytes=int(self.ram_bytes),
            disk_bytes=int(self.disk_bytes),
        )
        if self.gave_up:
            logger.warning(
                "spill cache gave up: stream exceeds the %.1f GiB RAM "
                "budget and no SWIFTLY_SPILL_DIR is set — backward "
                "passes will fall back to forward replay",
                self.budget_bytes / 2**30,
            )
        return self.complete

    # -- consume ------------------------------------------------------------

    def __len__(self):
        return len(self._meta)

    def meta(self, k):
        return self._meta[k]

    def get(self, k):
        """Entry k as a host ndarray (RAM hit or a full disk read).
        Disk reads retry transient failures with backoff; a read that
        stays failed raises (the streamed consumer then falls back to
        forward replay — see `StreamedForward.stream_column_groups`)."""
        with self._read_gate():
            kind, payload = self._entries[k]

            def read():
                fault_point("spill.read")
                if kind == "ram":
                    return payload
                with _metrics.stage("spill.disk_read") as st:
                    arr = np.load(payload)
                    st.bytes_moved = int(arr.nbytes)
                return arr

            out = retry_transient(read, site="spill.read")
        if kind == "ram":
            self._bump("ram_reads")
        else:
            self._bump("disk_reads")
            _metrics.count("spill.disk_reads")
        return out

    def get_row(self, k, index):
        """One sub-array of entry k (e.g. ``(c, s)`` of a [G, S, ...]
        group stack) without materialising the whole entry.

        The serving path (`parallel.streamed.CachedColumnFeed`) reads
        single subgrids out of recorded streams; RAM entries slice in
        place and disk entries go through a read-only memmap, so a
        one-subgrid request against a multi-GiB disk entry costs one
        row's IO, not the entry's. Registers with the reader–writer
        gate: a read that races `begin_patch` raises `StreamMidPatch`
        (a LookupError — the serving path's fall-back-to-compute
        signal), and the patch itself waits for in-flight reads.
        """
        with self._read_gate():
            kind, payload = self._entries[k]

            def read():
                fault_point("spill.get_row")
                if kind == "ram":
                    return payload[index]
                with _metrics.stage("spill.disk_read") as st:
                    row = np.array(np.load(payload, mmap_mode="r")[index])
                    st.bytes_moved = int(row.nbytes)
                return row

            out = retry_transient(read, site="spill.get_row")
        if kind == "ram":
            self._bump("ram_reads")
        else:
            self._bump("disk_reads")
            _metrics.count("spill.disk_reads")
        return out

    # -- patch --------------------------------------------------------------

    def begin_patch(self):
        """Mark the cache mid-patch: `parallel.streamed.CachedColumnFeed`
        refuses lookups while the mark is set, so a live feed can never
        observe a partially-patched stream — its consumers fall back to
        compute at their pinned version. The patcher clears the mark
        with `end_patch` AFTER re-stamping ``stream_version``, so there
        is no window in which a superseded feed serves.

        Writer side of the reader–writer gate: after raising the mark
        (which bounces NEW row reads with `StreamMidPatch`) it waits for
        in-flight reads to drain, so the rewrite never races a reader
        that passed the feed's gate check just before the mark went up.
        """
        deadline = time.monotonic() + _PATCH_DRAIN_TIMEOUT_S
        with self._readers:
            self.patching = True
            self._patcher_tid = threading.get_ident()
            while self._active_readers:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    logger.warning(
                        "begin_patch proceeding with %d reader(s) still "
                        "in flight after %.1fs — a reader thread looks "
                        "dead", self._active_readers,
                        _PATCH_DRAIN_TIMEOUT_S,
                    )
                    break
                self._readers.wait(timeout=remaining)
        _trace.instant("spill.begin_patch", cat="spill")

    def end_patch(self):
        """Clear the mid-patch mark (see `begin_patch`)."""
        with self._readers:
            self.patching = False
            self._patcher_tid = None
            self._readers.notify_all()
        _trace.instant("spill.end_patch", cat="spill")

    def patch_entry(self, k, delta):
        """Add ``delta`` into entry k — the incremental engine's cache
        patch (`delta.IncrementalForward`).

        Atomic AND idempotent per entry: a RAM entry is patched out of
        place (the retried closure only reads the old array, computes
        ``base + delta`` fresh and swaps the entry reference, so a
        transient failure at ANY point — even after a partial
        application would have happened in place — retries from the
        unmodified base and can never double-apply); a disk entry is
        read, added, and rewritten through the same tmp-sibling +
        rename path as the fill — a crash mid-patch leaves the old
        entry intact, never a torn one. A failure that outlives the
        retries raises; the caller's ladder degrades to a full
        re-record.
        """
        with self._lock:
            kind, payload = self._entries[k]
        delta = np.asarray(delta)
        base = self.get(k)
        if base.shape != delta.shape:
            raise ValueError(
                f"patch shape {delta.shape} != entry {k} shape "
                f"{base.shape}"
            )
        add = delta.astype(base.dtype, copy=False)
        if kind == "ram":

            def write():
                fault_point("spill.write")
                with _metrics.stage("spill.patch") as st:
                    # out of place: recomputed from the unmodified
                    # `payload` on every retry; the entry swap is one
                    # reference assignment under the lock, atomic for
                    # concurrent reads (which hold old-array views)
                    with self._lock:
                        self._entries[k] = ("ram", payload + add)
                    st.bytes_moved = int(add.nbytes)

            retry_transient(write, site="spill.write")
        else:
            with _metrics.stage("spill.patch") as st:
                self._disk_write(k, base + add)
                st.bytes_moved = int(add.nbytes)
        self._bump("patches")
        _metrics.count("spill.patches")
        _trace.instant("spill.patch", cat="spill", entry=int(k),
                       nbytes=int(add.nbytes))

    # -- maintenance --------------------------------------------------------

    def reset(self):
        """Back to empty (disk files deleted, orphaned ``.tmp`` files
        swept); counters are kept."""
        self._clear_entries()
        self._sweep_orphans()
        with self._lock:
            self.complete = False
            self.gave_up = False

    def stats(self):
        """JSON-ready summary for bench artifacts."""
        out = {
            "entries": len(self._entries),
            "complete": self.complete,
            "ram_bytes": int(self.ram_bytes),
            "disk_bytes": int(self.disk_bytes),
            "budget_bytes": int(self.budget_bytes),
            "disk_backed": self.spill_dir is not None,
            "stream_version": int(self.stream_version),
            **self.counters,
        }
        if self.policy is not None:
            out["policy"] = dict(self.policy)
        return out

    def export_manifest(self):
        """Describe this cache for a reader in ANOTHER process.

        Forces every RAM-resident entry down to its atomic on-disk form
        (`_disk_write`: tmp sibling + rename, so a reader can never map
        a torn entry) and returns a picklable manifest —
        ``{dir, entries, meta, stream_version}`` — that
        `serve.procfleet.SharedSpillReader` turns back into a read-only
        `get_row` surface over memory-mapped files. The entry files are
        immutable once exported; liveness state (``patching`` /
        ``complete`` / ``stream_version``) travels separately through
        the fleet's stream-state file so the owning process can gate
        cross-process readers exactly like in-process ones.
        """
        if not self.complete:
            raise RuntimeError("export_manifest requires a complete cache")
        if self.spill_dir is None:
            raise RuntimeError(
                "export_manifest requires a disk-backed cache (spill_dir)")
        with self._lock:
            if self.patching:
                raise RuntimeError("export_manifest mid-patch")
            for k, (kind, payload) in enumerate(self._entries):
                if kind == "ram":
                    path = self._disk_write(k, payload)
                    self._entries[k] = ("disk", path)
                    self.ram_bytes -= int(payload.nbytes)
                    self.disk_bytes += int(payload.nbytes)
                    self._bump("exported_entries")
            entries = [payload for (_kind, payload) in self._entries]
            meta = list(self._meta)
        _metrics.count("spill.manifest_exports")
        return {
            "dir": self._own_dir,
            "entries": entries,
            "meta": meta,
            "stream_version": int(self.stream_version),
        }

    def _clear_entries(self):
        with self._lock:
            self._entries = []
            self._meta = []
            self.ram_bytes = 0
            self.disk_bytes = 0
            own_dir, self._own_dir = self._own_dir, None
        if own_dir is not None:
            shutil.rmtree(own_dir, ignore_errors=True)

    def _sweep_orphans(self):
        """Remove ``.tmp`` siblings a crashed fill left behind — in this
        cache's own dir and in stale ``swiftly_spill_*`` dirs of a dead
        process under the shared spill dir. An orphaned tmp is a torn
        write; left in place it wastes disk and, worse, a later rename
        collision could surface it as a truncated entry."""
        roots = []
        if self._own_dir is not None:
            roots.append(self._own_dir)
        if self.spill_dir is not None and os.path.isdir(self.spill_dir):
            roots.append(os.path.join(self.spill_dir, "swiftly_spill_*"))
        swept = 0
        for root in roots:
            for tmp in glob.glob(os.path.join(root, "*.npy.tmp")):
                try:
                    os.remove(tmp)
                    swept += 1
                except OSError:  # pragma: no cover - concurrent sweep
                    pass
        if swept:
            logger.warning(
                "swept %d orphaned spill .tmp file(s) from a crashed "
                "fill", swept,
            )
            _metrics.count("spill.orphans_swept", swept)

    def _disk_write(self, k, array):
        """Chunked memmap write of one entry under the spill dir —
        ATOMIC (tmp sibling + rename: a crash mid-write can never leave
        a truncated ``group_*.npy`` that poisons a later cache-fed
        pass) and retried on transient I/O failure."""
        if self._own_dir is None:
            os.makedirs(self.spill_dir, exist_ok=True)
            self._own_dir = tempfile.mkdtemp(
                prefix="swiftly_spill_", dir=self.spill_dir
            )
        path = os.path.join(self._own_dir, f"group_{k:05d}.npy")

        def write():
            fault_point("spill.write")
            tmp = path + ".tmp"
            with _metrics.stage("spill.disk_write") as st:
                mm = np.lib.format.open_memmap(
                    tmp, mode="w+", dtype=array.dtype, shape=array.shape
                )
                row_bytes = max(1, array[:1].nbytes) if array.ndim else 1
                step = max(1, int(_DISK_CHUNK_BYTES // row_bytes))
                for s in range(0, array.shape[0], step):
                    mm[s : s + step] = array[s : s + step]
                mm.flush()
                del mm
                st.bytes_moved = int(array.nbytes)
            os.replace(tmp, path)
            return path

        return retry_transient(write, site="spill.write")

    def __del__(self):  # pragma: no cover - interpreter-shutdown path
        try:
            if self._own_dir is not None:
                shutil.rmtree(self._own_dir, ignore_errors=True)
        except Exception:
            pass
