"""Persistent XLA compilation cache.

The fused transform programs take minutes to compile at large N (the
sampled-DFT facet pass at 32k compiles for ~5 minutes on a
remote-compile TPU runtime); the persistent cache makes that a
once-per-machine cost instead of once-per-process.
"""

from __future__ import annotations

import os

__all__ = ["enable_compilation_cache"]


def enable_compilation_cache(cache_dir=None, min_compile_secs=1.0):
    """Cache compiled XLA executables on disk across processes.

    :param cache_dir: directory for the cache (default
        $JAX_COMPILATION_CACHE_DIR or ~/.cache/swiftly-tpu-xla)
    :param min_compile_secs: only cache programs that took at least this
        long to compile
    """
    import jax

    if cache_dir is None:
        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(
                os.path.expanduser("~"), ".cache", "swiftly-tpu-xla"
            ),
        )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_secs)
    )
    return cache_dir
