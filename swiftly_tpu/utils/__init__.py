"""Utilities: profiling, memory accounting, FLOP models, checkpointing,
compilation cache, subgrid-stream spill cache."""

from .cache import enable_compilation_cache
from .checkpoint import (
    CorruptCheckpointError,
    checkpoint_generations,
    restore_backward_state,
    restore_streamed_backward_state,
    save_backward_state,
    save_streamed_backward_state,
    verify_checkpoint,
)
from .flops import (
    backward_batched_flops,
    backward_sampled_flops,
    bwd_column_pass_flops,
    bwd_fold_flops,
    column_pass_flops,
    fft_flops,
    forward_batched_flops,
    forward_sampled_flops,
    peak_tflops,
    sampled_facet_pass_flops,
)
from .spill import SpillCache, spill_budget_bytes
from .profiling import (
    MemorySampler,
    collective_bytes_backward,
    collective_bytes_forward,
    column_collective_bytes,
    device_memory_stats,
    trace,
)

__all__ = [
    "CorruptCheckpointError",
    "MemorySampler",
    "backward_batched_flops",
    "checkpoint_generations",
    "backward_sampled_flops",
    "bwd_column_pass_flops",
    "bwd_fold_flops",
    "collective_bytes_backward",
    "collective_bytes_forward",
    "column_collective_bytes",
    "column_pass_flops",
    "device_memory_stats",
    "enable_compilation_cache",
    "fft_flops",
    "forward_batched_flops",
    "forward_sampled_flops",
    "peak_tflops",
    "restore_backward_state",
    "restore_streamed_backward_state",
    "save_backward_state",
    "save_streamed_backward_state",
    "sampled_facet_pass_flops",
    "SpillCache",
    "spill_budget_bytes",
    "trace",
    "verify_checkpoint",
]
