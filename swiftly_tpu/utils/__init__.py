"""Utilities: profiling, memory accounting, logging."""

from .profiling import (
    MemorySampler,
    collective_bytes_backward,
    collective_bytes_forward,
    device_memory_stats,
    trace,
)

__all__ = [
    "MemorySampler",
    "collective_bytes_backward",
    "collective_bytes_forward",
    "device_memory_stats",
    "trace",
]
