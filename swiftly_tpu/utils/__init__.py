"""Utilities: profiling, memory accounting, compilation cache, logging."""

from .cache import enable_compilation_cache
from .profiling import (
    MemorySampler,
    collective_bytes_backward,
    collective_bytes_forward,
    device_memory_stats,
    trace,
)

__all__ = [
    "MemorySampler",
    "collective_bytes_backward",
    "collective_bytes_forward",
    "device_memory_stats",
    "enable_compilation_cache",
    "trace",
]
