"""Checkpoint/resume for streaming transforms — hardened.

A `SwiftlyBackward` session is a long-running accumulation (hours at 64k
scale); its state is exactly (a) the per-facet accumulators, (b) the live
per-column accumulators in the LRU, and (c) which subgrids have been
folded in. This module snapshots that state to a single ``.npz`` so a
killed run resumes without recomputing finished subgrids.

(The reference has no checkpointing — its docs mention removed HDF5
subgrid dumps; this implements the "streaming accumulators as checkpoint
units" design its architecture implies.)

Durability discipline (the resilience layer's contract,
docs/resilience.md):

* **Atomic writes.** Every snapshot lands via tmp + ``fsync`` +
  ``os.replace`` — a crash mid-save can truncate only the tmp file,
  never the live checkpoint (the pre-hardening failure mode: a crash
  inside ``np.savez`` left a torn ``.npz`` that poisoned the resume).
* **Per-array CRC32.** Each array's checksum is stored in the snapshot
  meta and verified on restore; silent disk corruption raises
  :class:`CorruptCheckpointError` instead of folding garbage.
* **Keep-N generations.** Saves rotate ``path`` -> ``path.1`` ->
  ``path.2`` ... (``SWIFTLY_CKPT_KEEP`` total, default 3); restore
  falls back generation by generation past corrupt/truncated snapshots
  (counted as ``ckpt.fallbacks`` and recorded in the degradation
  ledger), so one bad write costs a few columns of recompute, not the
  run.
* **Fault sites.** ``checkpoint.save`` / ``checkpoint.save.done`` /
  ``checkpoint.restore`` are `resilience.faults` hook points — the
  chaos drill corrupts and kills here on a schedule.
* **Cross-layout migration.** Streamed snapshots record the mesh
  layout they were sharded with; restoring onto a DIFFERENT device
  count (the elastic-recovery case: a shard died and the survivors
  re-planned) migrates the saved facet stacks — real facets kept,
  layout padding regrown, arrays re-placed onto the new mesh — exactly,
  so a migrated resume stays bit-identical to an undisturbed run.
* **Observability.** The ``ckpt.save`` / ``ckpt.restore`` stage timers
  double as trace spans when `obs.trace` is on (the metrics→trace
  bridge), so a recorded timeline shows save/restore windows — with
  bytes attribution — inline with the passes they interrupt, and
  generation fallbacks land as ``degrade.checkpoint.*`` instants.

Config-mismatch errors (wrong params/backend/kind/version) are
deliberately NOT retried against older generations: every generation
was written by the same session, so a mismatch is a caller bug and
must surface loudly.
"""

from __future__ import annotations

import json
import logging
import os
import zlib

import numpy as np

from ..obs import metrics as _metrics
from ..resilience import degrade as _degrade
from ..resilience.faults import fault_point

__all__ = [
    "CorruptCheckpointError",
    "checkpoint_generations",
    "ckpt_keep",
    "restore_backward_state",
    "restore_streamed_backward_state",
    "save_backward_state",
    "save_streamed_backward_state",
    "verify_checkpoint",
]

logger = logging.getLogger(__name__)

# v2 adds per-array CRC32 checksums to the meta; v1 snapshots (no
# checksums) still restore — integrity verification is skipped for them.
_VERSION = 2
_SUPPORTED_VERSIONS = (1, 2)


class CorruptCheckpointError(ValueError):
    """The snapshot file is unreadable or fails integrity verification
    (truncated archive, bad CRC, undecodable meta). Restore treats this
    as a damaged *generation* and falls back; config mismatches raise
    plain ``ValueError`` and do not."""


def ckpt_keep(default=3):
    """Total checkpoint generations kept (``SWIFTLY_CKPT_KEEP``, >= 1)."""
    try:
        return max(1, int(os.environ.get("SWIFTLY_CKPT_KEEP", default)))
    except ValueError:
        return default


def checkpoint_generations(path):
    """Existing generation files for `path`, newest first."""
    path = str(path)
    out = [path] if os.path.exists(path) else []
    k = 1
    while os.path.exists(f"{path}.{k}"):
        out.append(f"{path}.{k}")
        k += 1
    return out


def _crc(arr) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).view(np.uint8).data)


def _rotate(path, keep):
    """Shift path -> path.1 -> ... -> path.(keep-1); the oldest drops."""
    if keep <= 1 or not os.path.exists(path):
        return
    for k in range(keep - 1, 0, -1):
        src = path if k == 1 else f"{path}.{k - 1}"
        dst = f"{path}.{k}"
        if os.path.exists(src):
            os.replace(src, dst)


def _atomic_savez(path, arrays, meta):
    """Checksummed meta + atomic tmp/fsync/rename write + rotation."""
    path = str(path)
    fault_point("checkpoint.save", path)
    meta = dict(meta)
    meta["crc"] = {name: _crc(arr) for name, arr in arrays.items()}
    meta_bytes = json.dumps(meta).encode()
    arrays["meta"] = np.frombuffer(meta_bytes, dtype=np.uint8)
    # the meta's own integrity: a bit-flip inside the JSON could parse
    # to a silently different session description
    arrays["meta_crc"] = np.asarray(
        [zlib.crc32(meta_bytes)], dtype=np.uint32
    )
    tmp = path + ".tmp"
    with _metrics.stage("ckpt.save") as st:
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrays)
            fh.flush()
            os.fsync(fh.fileno())
        _rotate(path, ckpt_keep())
        os.replace(tmp, path)
        st.bytes_moved = int(os.path.getsize(path))
    _metrics.count("ckpt.saves")
    # post-landing hook: a "corrupt" fault flips a byte in the final
    # file — the generation the next restore must detect and skip
    fault_point("checkpoint.save.done", path)


def _open_verified(path):
    """np.load the snapshot and parse+verify its meta; any structural
    failure (torn zip, undecodable meta) -> CorruptCheckpointError."""
    try:
        data = np.load(path)
    except Exception as exc:
        raise CorruptCheckpointError(
            f"checkpoint {path!r} unreadable: {type(exc).__name__}: {exc}"
        ) from exc
    try:
        meta_bytes = bytes(data["meta"].tobytes())
        if "meta_crc" in data.files:
            want = int(data["meta_crc"][0])
            got = zlib.crc32(meta_bytes)
            if got != want:
                raise CorruptCheckpointError(
                    f"checkpoint {path!r} meta failed CRC32 "
                    f"verification (stored {want}, got {got})"
                )
        meta = json.loads(meta_bytes.decode())
    except CorruptCheckpointError:
        data.close()
        raise
    except Exception as exc:
        data.close()
        raise CorruptCheckpointError(
            f"checkpoint {path!r} meta undecodable: "
            f"{type(exc).__name__}: {exc}"
        ) from exc
    return data, meta


def _load_array(data, meta, name, path):
    """One array out of the snapshot, CRC-verified when the snapshot
    carries checksums (v2+)."""
    try:
        arr = data[name]
    except Exception as exc:
        raise CorruptCheckpointError(
            f"checkpoint {path!r} array {name!r} unreadable: "
            f"{type(exc).__name__}: {exc}"
        ) from exc
    want = (meta.get("crc") or {}).get(name)
    if want is not None and _crc(arr) != want:
        raise CorruptCheckpointError(
            f"checkpoint {path!r} array {name!r} failed CRC32 "
            f"verification (stored {want}, got {_crc(arr)})"
        )
    return arr


def verify_checkpoint(path):
    """Integrity problems with the snapshot at `path` (empty = good).

    Reads every array and checks its CRC — the offline twin of what
    restore does, for drills and operators (``python -c`` one-liner in
    docs/resilience.md)."""
    problems = []
    try:
        data, meta = _open_verified(str(path))
    except CorruptCheckpointError as exc:
        return [str(exc)]
    with data:
        if meta.get("version") not in _SUPPORTED_VERSIONS:
            problems.append(f"unsupported version {meta.get('version')!r}")
        if meta.get("version", 0) >= 2 and "crc" not in meta:
            problems.append("v2 snapshot missing crc table")
        for name in data.files:
            if name == "meta":
                continue
            try:
                _load_array(data, meta, name, str(path))
            except CorruptCheckpointError as exc:
                problems.append(str(exc))
    return problems


def _restore_with_fallback(path, restore_one):
    """Run `restore_one(generation)` against path, then older
    generations, skipping corrupt snapshots (counted + recorded)."""
    gens = checkpoint_generations(path)
    if not gens:
        raise FileNotFoundError(f"no checkpoint at {path!r}")
    last_exc = None
    for k, gen in enumerate(gens):
        try:
            fault_point("checkpoint.restore", gen)
            with _metrics.stage("ckpt.restore"):
                out = restore_one(gen)
            if k:
                _metrics.count("ckpt.fallbacks", k)
                _degrade.record(
                    "checkpoint", "fallback_generation",
                    f"{path!r} generations 0..{k - 1} corrupt; "
                    f"restored {gen!r}",
                )
                logger.warning(
                    "checkpoint %r corrupt; restored previous "
                    "generation %r", path, gen,
                )
            return out
        except CorruptCheckpointError as exc:
            last_exc = exc
            logger.warning("checkpoint generation %r: %s", gen, exc)
            continue
    raise CorruptCheckpointError(
        f"all {len(gens)} checkpoint generation(s) of {path!r} are "
        f"corrupt (last: {last_exc})"
    ) from last_exc


def save_backward_state(path, backward, processed_subgrids=None):
    """Snapshot a SwiftlyBackward session to `path` (.npz): atomic,
    checksummed, keep-N rotated.

    :param backward: the SwiftlyBackward instance
    :param processed_subgrids: optional list of (off0, off1) already folded
        in, stored for the caller to skip on resume
    """
    core = backward.core
    arrays = {}
    meta = {
        "version": _VERSION,
        "kind": "backward",
        "backend": core.backend,
        "params": [core.W, core.N, core.xM_size, core.yN_size],
        "n_real": backward.stack.n_real,
        "n_total": backward.stack.n_total,
        "lru_keys": [],
        "processed": list(map(list, processed_subgrids or [])),
        "has_mnaf": backward._MNAF_BMNAFs is not None,
    }
    if backward._MNAF_BMNAFs is not None:
        arrays["MNAF_BMNAFs"] = np.asarray(backward._MNAF_BMNAFs)
    for key, col in backward.lru._store.items():
        meta["lru_keys"].append(int(key))
        arrays[f"lru_{int(key)}"] = np.asarray(col)
    _atomic_savez(path, arrays, meta)


def restore_backward_state(path, backward):
    """Restore a snapshot into a freshly constructed SwiftlyBackward.

    The instance must be built with the same config/facet list as the one
    saved. Corrupt generations fall back to the previous good one.
    Returns the list of (off0, off1) subgrids already processed.
    """
    return _restore_with_fallback(
        path, lambda gen: _restore_backward_one(gen, backward)
    )


def _restore_backward_one(path, backward):
    data, meta = _open_verified(path)
    with data:
        core = backward.core
        _check_meta(meta, core, backward.stack.n_total, "backward")

        mesh = getattr(backward, "mesh", None)

        def _dev(arr):
            if core.backend in ("numpy", "native"):
                return np.array(arr)
            import jax
            import jax.numpy as jnp

            arr = jnp.asarray(arr)
            if mesh is not None:
                # Restore the facet-sharded layout the accumulators were
                # created with (api._place); without this a mesh session
                # resumes with everything on one device. Multihost-safe
                # (each process touches only its addressable shards).
                from ..parallel.mesh import place_facet_sharded

                arr = place_facet_sharded(np.asarray(arr), mesh)
            return arr

        if meta["has_mnaf"]:
            backward._MNAF_BMNAFs = _dev(
                _load_array(data, meta, "MNAF_BMNAFs", path)
            )
        for key in meta["lru_keys"]:
            backward.lru.set(
                key, _dev(_load_array(data, meta, f"lru_{key}", path))
            )
        return [tuple(p) for p in meta["processed"]]


def _check_meta(meta, core, n_total, kind, n_real=None):
    if meta["version"] not in _SUPPORTED_VERSIONS:
        raise ValueError(f"Unsupported checkpoint version {meta['version']}")
    # legacy files (written by save_backward_state before "kind" existed)
    # default to "backward" so a cross-kind restore fails loudly here
    if meta.get("kind", "backward") != kind:
        raise ValueError(
            f"Checkpoint holds {meta.get('kind')!r} state, expected {kind!r}"
        )
    expect = [core.W, core.N, core.xM_size, core.yN_size]
    if meta["params"] != expect or meta["backend"] != core.backend:
        raise ValueError(
            f"Checkpoint was written for params {meta['params']} "
            f"backend {meta['backend']!r}; this session has {expect} "
            f"backend {core.backend!r}"
        )
    if n_real is not None:
        # cross-layout migration: the padded stack size is a property of
        # the LAYOUT (facets round up to a shard multiple), so only the
        # REAL facet count must match — padding facets are exactly zero
        # and are dropped/regrown by `_migrate_stack`
        if meta.get("n_real") != n_real:
            raise ValueError("Facet stack size mismatch")
    elif meta["n_total"] != n_total:
        raise ValueError("Facet stack size mismatch")


def _migrate_stack(arr, n_real, n_total):
    """Re-shape a saved facet-stacked array (axis 0 = facets, padded to
    the SAVING layout's shard multiple) for a different layout: keep the
    `n_real` real facets, re-pad with zeros to the new `n_total`.

    Exact by construction: padding facets are zero-masked in the forward
    and fold to zero in the backward whatever layout assumes them, so
    dropping one layout's padding and growing another's changes no real
    accumulator byte — the migrated restore stays bit-identical.
    """
    arr = np.asarray(arr)
    if arr.shape[0] < n_real:
        raise CorruptCheckpointError(
            f"facet-stacked array holds {arr.shape[0]} facets; "
            f"{n_real} real facets expected"
        )
    arr = arr[:n_real]
    if arr.shape[0] < n_total:
        pad = np.zeros(
            (n_total - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype
        )
        arr = np.concatenate([arr, pad], axis=0)
    return arr


def save_streamed_backward_state(path, backward, processed_subgrids=None):
    """Snapshot a StreamedBackward session to `path` (.npz): atomic,
    checksummed, keep-N rotated.

    The streamed backward's whole state is its per-column NAF_BMNAF row
    accumulators (`_naf`, one [F, m, yB_pad] array per seen column) —
    the path actually used at 32k+ scale, where a killed run would
    otherwise lose hours of accumulation.

    :param backward: the StreamedBackward instance
    :param processed_subgrids: optional list of (off0, off1) already folded
        in, stored for the caller to skip on resume; defaults to the
        backward's own ``processed`` ledger when it has one
    """
    core = backward.core
    if processed_subgrids is None:
        processed_subgrids = getattr(backward, "processed", None)
    arrays = {}
    from ..parallel.mesh import FACET_AXIS, mesh_size

    mesh = backward._base.mesh
    meta = {
        "version": _VERSION,
        "kind": "streamed_backward",
        "backend": core.backend,
        "params": [core.W, core.N, core.xM_size, core.yN_size],
        "n_real": backward.stack.n_real,
        "n_total": backward.stack.n_total,
        "residency": backward._base.residency,
        "yB_pad": backward._base._yB_pad,
        "naf_keys": [],
        "processed": list(map(list, processed_subgrids or [])),
        # monotone facet-stack version (delta.FacetDeltaLedger): a
        # resume can tell whether the accumulators predate a facet
        # update; 0 = unversioned, absent tolerated on restore
        "stream_version": int(getattr(backward, "stream_version", 0)),
        # the mesh layout the accumulators were sharded with: resume
        # must restore onto the SAME sharding (facet padding and shard
        # ownership both depend on it) — None for single-device sessions
        "mesh": (
            {
                "n_devices": int(mesh_size(mesh)),
                "facet_shards": int(mesh_size(mesh)),
                "axis": FACET_AXIS,
            }
            if mesh is not None
            else None
        ),
    }
    if backward._base.residency == "sampled":
        # the whole state is the image-space accumulator (pending rows
        # fold first so the snapshot is self-contained)
        backward._flush_folds()
        meta["has_acc"] = backward._acc is not None
        slab = getattr(backward, "_row_slab", None)
        meta["row_slab"] = list(slab) if slab else None
        if backward._acc is not None:
            arrays["acc"] = np.asarray(backward._acc)
    for key, rows in backward._naf.items():
        meta["naf_keys"].append(int(key))
        arrays[f"naf_{int(key)}"] = np.asarray(rows)
    _atomic_savez(path, arrays, meta)


def restore_streamed_backward_state(path, backward):
    """Restore a snapshot into a freshly constructed StreamedBackward.

    The instance must be built with the same config/facet list (and may
    use either residency — accumulators are re-placed to match). The
    MESH LAYOUT may differ from the saving session's: snapshots written
    on an N-device mesh migrate onto any other device count (including
    single-chip, and vice versa) via gather→re-shard — the real facets
    are kept, layout padding is regrown, and the arrays are re-placed
    onto the new mesh (counted as ``ckpt.migrations`` and recorded in
    the degradation ledger). Corrupt generations fall back to the
    previous good one; fallback and migration compose. Returns the list
    of (off0, off1) subgrids already processed (also assigned to
    ``backward.processed``).
    """
    return _restore_with_fallback(
        path, lambda gen: _restore_streamed_one(gen, backward)
    )


def _restore_streamed_one(path, backward):
    data, meta = _open_verified(path)
    with data:
        core = backward.core
        migrate = False
        saved_mesh = have_mesh = None
        if "mesh" in meta:
            # pre-mesh snapshots lack the key entirely (no migration —
            # they restore unchanged onto the layout they were written
            # on); new snapshots always record it, None meaning
            # single-device. A layout mismatch is no longer a refusal:
            # the elastic recovery ladder depends on restoring the last
            # autosave onto whatever mesh SURVIVED, so mismatched
            # snapshots take the gather→re-shard migration path — the
            # saved arrays are already gathered host copies, so
            # migration is a facet re-pad plus `_place` onto the new
            # mesh, exact by construction (see `_migrate_stack`).
            from ..parallel.mesh import mesh_size

            saved_mesh = (meta["mesh"] or {}).get("n_devices", 1)
            have_mesh = mesh_size(backward._base.mesh)
            migrate = saved_mesh != have_mesh
        _check_meta(
            meta, core, backward.stack.n_total, "streamed_backward",
            n_real=backward.stack.n_real if migrate else None,
        )
        n_real, n_total = backward.stack.n_real, backward.stack.n_total

        def _stack(arr):
            return _migrate_stack(arr, n_real, n_total) if migrate else arr

        if migrate:
            _metrics.count("ckpt.migrations")
            _degrade.record(
                "checkpoint", "migrate_layout",
                f"{path!r} written on a {saved_mesh}-device layout; "
                f"migrated onto {have_mesh} device(s)",
            )
            logger.warning(
                "checkpoint %r: migrating %s-device layout onto %s "
                "device(s)", path, saved_mesh, have_mesh,
            )
        saved_res = meta.get("residency")
        is_sampled = backward._base.residency == "sampled"
        if (saved_res == "sampled") != is_sampled:
            raise ValueError(
                f"Checkpoint holds residency={saved_res!r} state; this "
                f"session uses {backward._base.residency!r} (the sampled "
                f"accumulator and NAF rows are not interchangeable)"
            )
        processed = [tuple(p) for p in meta["processed"]]
        if is_sampled:
            saved_slab = meta.get("row_slab")
            have_slab = getattr(backward, "_row_slab", None)
            if (saved_slab or None) != (
                list(have_slab) if have_slab else None
            ):
                # a slab accumulator restored at a different row window
                # would fold garbage silently — refuse
                raise ValueError(
                    f"Checkpoint holds row_slab={saved_slab} state; this "
                    f"session uses row_slab="
                    f"{list(have_slab) if have_slab else None}"
                )
            if meta.get("has_acc"):
                backward._acc = backward._base._place(
                    _stack(_load_array(data, meta, "acc", path))
                )
            backward.processed = list(processed)
            return processed
        # older snapshots (same meta layout) did not record yB_pad; the
        # rows arrays carry it as their last data axis either way
        saved_pad = meta.get("yB_pad")
        if saved_pad is None and meta["naf_keys"]:
            # rows are [F, m, yB_pad] (+ trailing planar pair axis)
            saved_pad = _load_array(
                data, meta, f"naf_{meta['naf_keys'][0]}", path
            ).shape[2]
        if saved_pad is not None and saved_pad != backward._base._yB_pad:
            # rows are stored at the saving session's col_block padding;
            # a different padding would make finish() slice garbage
            raise ValueError(
                f"Checkpoint rows are padded to yB_pad={saved_pad} "
                f"(col_block of the saving session); this session uses "
                f"{backward._base._yB_pad} — construct StreamedBackward "
                f"with the same col_block"
            )

        device = backward._base.residency == "device"
        for key in meta["naf_keys"]:
            rows = _stack(_load_array(data, meta, f"naf_{key}", path))
            if device:
                # facet-sharded on a mesh, plain device array otherwise
                backward._naf[key] = backward._base._place(rows)
            else:
                backward._naf[key] = np.array(rows)
        backward.processed = list(processed)
        return processed
