"""Checkpoint/resume for streaming transforms.

A `SwiftlyBackward` session is a long-running accumulation (hours at 64k
scale); its state is exactly (a) the per-facet accumulators, (b) the live
per-column accumulators in the LRU, and (c) which subgrids have been
folded in. This module snapshots that state to a single ``.npz`` so a
killed run resumes without recomputing finished subgrids.

(The reference has no checkpointing — its docs mention removed HDF5
subgrid dumps; this implements the "streaming accumulators as checkpoint
units" design its architecture implies.)
"""

from __future__ import annotations

import json

import numpy as np

__all__ = [
    "save_backward_state",
    "restore_backward_state",
    "save_streamed_backward_state",
    "restore_streamed_backward_state",
]

_VERSION = 1


def save_backward_state(path, backward, processed_subgrids=None):
    """Snapshot a SwiftlyBackward session to `path` (.npz).

    :param backward: the SwiftlyBackward instance
    :param processed_subgrids: optional list of (off0, off1) already folded
        in, stored for the caller to skip on resume
    """
    core = backward.core
    arrays = {}
    meta = {
        "version": _VERSION,
        "kind": "backward",
        "backend": core.backend,
        "params": [core.W, core.N, core.xM_size, core.yN_size],
        "n_real": backward.stack.n_real,
        "n_total": backward.stack.n_total,
        "lru_keys": [],
        "processed": list(map(list, processed_subgrids or [])),
        "has_mnaf": backward._MNAF_BMNAFs is not None,
    }
    if backward._MNAF_BMNAFs is not None:
        arrays["MNAF_BMNAFs"] = np.asarray(backward._MNAF_BMNAFs)
    for key, col in backward.lru._store.items():
        meta["lru_keys"].append(int(key))
        arrays[f"lru_{int(key)}"] = np.asarray(col)
    arrays["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    np.savez(path, **arrays)


def restore_backward_state(path, backward):
    """Restore a snapshot into a freshly constructed SwiftlyBackward.

    The instance must be built with the same config/facet list as the one
    saved. Returns the list of (off0, off1) subgrids already processed.
    """
    with np.load(path) as data:
        meta = json.loads(bytes(data["meta"].tobytes()).decode())
        core = backward.core
        _check_meta(meta, core, backward.stack.n_total, "backward")

        mesh = getattr(backward, "mesh", None)

        def _dev(arr):
            if core.backend in ("numpy", "native"):
                return np.array(arr)
            import jax
            import jax.numpy as jnp

            arr = jnp.asarray(arr)
            if mesh is not None:
                # Restore the facet-sharded layout the accumulators were
                # created with (api._place); without this a mesh session
                # resumes with everything on one device. Multihost-safe
                # (each process touches only its addressable shards).
                from ..parallel.mesh import place_facet_sharded

                arr = place_facet_sharded(np.asarray(arr), mesh)
            return arr

        if meta["has_mnaf"]:
            backward._MNAF_BMNAFs = _dev(data["MNAF_BMNAFs"])
        for key in meta["lru_keys"]:
            backward.lru.set(key, _dev(data[f"lru_{key}"]))
        return [tuple(p) for p in meta["processed"]]


def _check_meta(meta, core, n_total, kind):
    if meta["version"] != _VERSION:
        raise ValueError(f"Unsupported checkpoint version {meta['version']}")
    # legacy files (written by save_backward_state before "kind" existed)
    # default to "backward" so a cross-kind restore fails loudly here
    if meta.get("kind", "backward") != kind:
        raise ValueError(
            f"Checkpoint holds {meta.get('kind')!r} state, expected {kind!r}"
        )
    expect = [core.W, core.N, core.xM_size, core.yN_size]
    if meta["params"] != expect or meta["backend"] != core.backend:
        raise ValueError(
            f"Checkpoint was written for params {meta['params']} "
            f"backend {meta['backend']!r}; this session has {expect} "
            f"backend {core.backend!r}"
        )
    if meta["n_total"] != n_total:
        raise ValueError("Facet stack size mismatch")


def save_streamed_backward_state(path, backward, processed_subgrids=None):
    """Snapshot a StreamedBackward session to `path` (.npz).

    The streamed backward's whole state is its per-column NAF_BMNAF row
    accumulators (`_naf`, one [F, m, yB_pad] array per seen column) —
    the path actually used at 32k+ scale, where a killed run would
    otherwise lose hours of accumulation.

    :param backward: the StreamedBackward instance
    :param processed_subgrids: optional list of (off0, off1) already folded
        in, stored for the caller to skip on resume
    """
    core = backward.core
    arrays = {}
    meta = {
        "version": _VERSION,
        "kind": "streamed_backward",
        "backend": core.backend,
        "params": [core.W, core.N, core.xM_size, core.yN_size],
        "n_real": backward.stack.n_real,
        "n_total": backward.stack.n_total,
        "residency": backward._base.residency,
        "yB_pad": backward._base._yB_pad,
        "naf_keys": [],
        "processed": list(map(list, processed_subgrids or [])),
    }
    if backward._base.residency == "sampled":
        # the whole state is the image-space accumulator (pending rows
        # fold first so the snapshot is self-contained)
        backward._flush_folds()
        meta["has_acc"] = backward._acc is not None
        slab = getattr(backward, "_row_slab", None)
        meta["row_slab"] = list(slab) if slab else None
        if backward._acc is not None:
            arrays["acc"] = np.asarray(backward._acc)
    for key, rows in backward._naf.items():
        meta["naf_keys"].append(int(key))
        arrays[f"naf_{int(key)}"] = np.asarray(rows)
    arrays["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    np.savez(path, **arrays)


def restore_streamed_backward_state(path, backward):
    """Restore a snapshot into a freshly constructed StreamedBackward.

    The instance must be built with the same config/facet list (and may
    use either residency — accumulators are re-placed to match). Returns
    the list of (off0, off1) subgrids already processed.
    """
    with np.load(path) as data:
        meta = json.loads(bytes(data["meta"].tobytes()).decode())
        core = backward.core
        _check_meta(meta, core, backward.stack.n_total, "streamed_backward")
        saved_res = meta.get("residency")
        is_sampled = backward._base.residency == "sampled"
        if (saved_res == "sampled") != is_sampled:
            raise ValueError(
                f"Checkpoint holds residency={saved_res!r} state; this "
                f"session uses {backward._base.residency!r} (the sampled "
                f"accumulator and NAF rows are not interchangeable)"
            )
        if is_sampled:
            saved_slab = meta.get("row_slab")
            have_slab = getattr(backward, "_row_slab", None)
            if (saved_slab or None) != (
                list(have_slab) if have_slab else None
            ):
                # a slab accumulator restored at a different row window
                # would fold garbage silently — refuse
                raise ValueError(
                    f"Checkpoint holds row_slab={saved_slab} state; this "
                    f"session uses row_slab="
                    f"{list(have_slab) if have_slab else None}"
                )
            if meta.get("has_acc"):
                backward._acc = backward._base._place(data["acc"])
            return [tuple(p) for p in meta["processed"]]
        # older snapshots (same _VERSION) did not record yB_pad; the rows
        # arrays carry it as their last data axis either way
        saved_pad = meta.get("yB_pad")
        if saved_pad is None and meta["naf_keys"]:
            # rows are [F, m, yB_pad] (+ trailing planar pair axis)
            saved_pad = data[f"naf_{meta['naf_keys'][0]}"].shape[2]
        if saved_pad is not None and saved_pad != backward._base._yB_pad:
            # rows are stored at the saving session's col_block padding;
            # a different padding would make finish() slice garbage
            raise ValueError(
                f"Checkpoint rows are padded to yB_pad={saved_pad} "
                f"(col_block of the saving session); this session uses "
                f"{backward._base._yB_pad} — construct StreamedBackward "
                f"with the same col_block"
            )

        device = backward._base.residency == "device"
        for key in meta["naf_keys"]:
            rows = data[f"naf_{key}"]
            if device:
                # facet-sharded on a mesh, plain device array otherwise
                backward._naf[key] = backward._base._place(rows)
            else:
                backward._naf[key] = np.array(rows)
        return [tuple(p) for p in meta["processed"]]
