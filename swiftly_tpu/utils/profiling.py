"""Profiling and memory instrumentation.

The reference relies on Dask's performance_report + MemorySampler + worker
transfer logs (scripts/utils.py:166-231, demo_api.py:125-148). TPU
equivalents:

* `trace(dir)` — context manager writing a jax.profiler trace (viewable in
  Perfetto/TensorBoard) covering the wrapped region.
* `device_memory_stats()` — per-device live/peak byte counts.
* `MemorySampler` — periodic device-memory sampling into rows you can dump
  to CSV.
* `collective_bytes_forward/backward` — analytic transfer accounting: on a
  facet-sharded mesh the bytes moved per subgrid are exactly computable
  from the contribution size, replacing post-hoc Dask transfer-log
  scraping.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time

import numpy as np

logger = logging.getLogger(__name__)

# one-shot flag: warn the first time every device reports empty stats so
# operators know the memory artifacts they are writing carry no data
# (e.g. tunnel-attached runtimes that hide memory_stats()).
_warned_empty_stats = False

__all__ = [
    "MemorySampler",
    "collective_bytes_backward",
    "collective_bytes_forward",
    "column_collective_bytes",
    "device_memory_stats",
    "probe_hbm_bytes",
    "trace",
]

# probe_hbm_bytes result cache: None = not probed yet; 0 = probed, nothing
# measurable; >0 = usable HBM bytes
_probed_hbm = None

# Usable single-buffer HBM bytes by device kind, for runtimes that report
# no memory_stats at all (the tunnel-attached TPU this repo benches on).
# The v5e figure is MEASURED on that runtime (fresh-process single-buffer
# binary search, 2026-07-31: 16.5e9 allocates+sums fine, 17.0e9 fails;
# 16.5e9 recorded with the failing bound as margin). A deliberate
# over-allocation probe is NOT used: on this runtime allocation failures
# surface asynchronously on LATER ops and poison the whole client — a
# failed 64 GiB device_put "succeeds", then every subsequent allocation
# throws RESOURCE_EXHAUSTED. Other rows are the published HBM sizes less
# the same ~4% runtime reserve observed on v5e.
_HBM_BY_KIND = {
    "TPU v5 lite": 16.0e9,  # v5e: 16.5e9 measured, 0.5 GB multi-buffer margin
    "TPU v5e": 16.0e9,
    "TPU v5p": 91.0e9,  # 95 GB published
    "TPU v4": 31.0e9,  # 32 GB published
    "TPU v6e": 31.0e9,  # 32 GB published
}


def probe_hbm_bytes(device=None):
    """USABLE accelerator-memory bytes for budget sizing (margins already
    applied — callers subtract their own residents, not another safety
    factor).

    90% of `memory_stats()["bytes_limit"]` when the runtime reports it,
    else the measured per-device-kind table above (those figures are
    usable-as-measured, with a multi-buffer fragmentation margin baked
    in). Returns None on CPU or unknown devices (callers fall back to
    their own default). Result cached per process; SWIFTLY_HBM_PROBE=0
    disables.
    """
    import os

    global _probed_hbm
    if os.environ.get("SWIFTLY_HBM_PROBE", "1") == "0":
        return None
    if _probed_hbm is not None:
        return _probed_hbm or None
    import jax

    if device is None:
        device = jax.devices()[0]
    if device.platform == "cpu":
        return None
    try:
        limit = (device.memory_stats() or {}).get("bytes_limit", 0)
    except Exception:  # pragma: no cover - backend-specific
        limit = 0
    if limit:
        limit = 0.9 * limit  # reported TOTAL -> usable
    else:
        kind = str(getattr(device, "device_kind", "")).lower()
        for name, usable in _HBM_BY_KIND.items():
            if name.lower() in kind:
                limit = usable
                logger.info(
                    "memory_stats empty; using measured usable HBM for "
                    "%s: %.2f GB", name, usable / 1e9,
                )
                break
    _probed_hbm = int(limit)
    return _probed_hbm or None


@contextlib.contextmanager
def trace(log_dir=None):
    """Write a jax.profiler trace for the enclosed region (no-op if
    log_dir is None)."""
    if log_dir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def device_memory_stats() -> dict:
    """Per-device memory statistics (bytes_in_use, peak_bytes_in_use, ...).

    Returns an empty dict per device on backends that don't expose stats
    (e.g. CPU)."""
    import jax

    stats = {}
    for dev in jax.devices():
        try:
            stats[str(dev)] = dev.memory_stats() or {}
        except Exception:  # pragma: no cover - backend-specific
            stats[str(dev)] = {}
    global _warned_empty_stats
    if not _warned_empty_stats and not any(stats.values()):
        _warned_empty_stats = True
        logger.warning(
            "memory_stats() is empty on every device (%s) — memory "
            "reports/CSVs from this run will contain only zeros",
            ", ".join(stats) or "no devices",
        )
    return stats


class MemorySampler:
    """Samples device memory on a background thread.

    Usage::

        sampler = MemorySampler(interval=0.5)
        with sampler.sample():
            ... work ...
        rows = sampler.rows   # [(t, device, bytes_in_use), ...]
        sampler.to_csv("mem.csv")
    """

    def __init__(self, interval: float = 0.5):
        self.interval = interval
        self.rows = []
        self._stop = threading.Event()
        self._thread = None

    def _loop(self):
        t0 = time.time()
        while not self._stop.is_set():
            for dev, stats in device_memory_stats().items():
                self.rows.append(
                    (time.time() - t0, dev, stats.get("bytes_in_use", 0))
                )
            self._stop.wait(self.interval)

    @contextlib.contextmanager
    def sample(self):
        self.rows = []
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        try:
            yield self
        finally:
            self._stop.set()
            self._thread.join()

    def to_csv(self, path):
        with open(path, "w") as fh:
            fh.write("t_seconds,device,bytes_in_use\n")
            for t, dev, b in self.rows:
                fh.write(f"{t:.3f},{dev},{b}\n")

    def to_html(self, path, title="device memory"):
        """Self-contained HTML report: an inline-SVG memory timeline per
        device (the analogue of the reference demo's Dask
        performance-report HTML, reference demo_api.py:127-133)."""
        import html as _html

        title = _html.escape(str(title))
        by_dev = {}
        for t, dev, b in self.rows:
            by_dev.setdefault(str(dev), []).append((t, b))
        t_max = max((t for t, _, _ in self.rows), default=1.0) or 1.0
        b_max = max((b for _, _, b in self.rows), default=1) or 1
        W, H, PAD = 800, 240, 40
        # legend column to the right of the plot so labels never overlap
        # the curves, however many devices there are
        LEG = 180
        colors = ["#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#ff7f0e"]
        parts = [
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{title}</title></head><body>"
            f"<h2>{title}</h2>"
            f"<p>peak {b_max / 2**30:.2f} GiB over {t_max:.1f} s</p>"
            f"<svg width='{W + LEG}' height='{H}' "
            "style='background:#fafafa;border:1px solid #ccc'>"
        ]
        for i, (dev, pts) in enumerate(sorted(by_dev.items())):
            coords = [
                (
                    PAD + (W - 2 * PAD) * t / t_max,
                    H - PAD - (H - 2 * PAD) * b / b_max,
                )
                for t, b in pts
            ]
            c = colors[i % len(colors)]
            if len(coords) == 1:
                # a one-point polyline renders nothing: draw a dot
                x, y = coords[0]
                parts.append(
                    f"<circle cx='{x:.1f}' cy='{y:.1f}' r='3' "
                    f"fill='{c}'/>"
                )
            else:
                poly = " ".join(f"{x:.1f},{y:.1f}" for x, y in coords)
                parts.append(
                    f"<polyline points='{poly}' fill='none' stroke='{c}' "
                    f"stroke-width='1.5'/>"
                )
            parts.append(
                f"<text x='{W + 8}' y='{16 + 14 * i}' fill='{c}' "
                f"font-size='12'>{_html.escape(dev)}</text>"
            )
        parts.append(
            f"<text x='{PAD}' y='{H - 8}' font-size='11'>0 s</text>"
            f"<text x='{W - PAD - 30}' y='{H - 8}' font-size='11'>"
            f"{t_max:.0f} s</text>"
            f"<text x='2' y='{PAD}' font-size='11'>"
            f"{b_max / 2**30:.1f} GiB</text>"
            "</svg></body></html>"
        )
        with open(path, "w") as fh:
            fh.write("".join(parts))


def _itemsize(dtype, planar: bool) -> int:
    size = np.dtype(dtype).itemsize
    return 2 * size if planar else size


def collective_bytes_forward(
    xM_size: int, n_devices: int, dtype=np.float32, planar: bool = True,
) -> int:
    """Bytes crossing the mesh per forward subgrid (analytic).

    Each device contributes a partial padded subgrid [xM, xM]; a ring
    all-reduce over d devices moves 2*(d-1) buffers in total.
    """
    buf = xM_size * xM_size * _itemsize(dtype, planar)
    return int(buf * 2 * (n_devices - 1))


def collective_bytes_backward(
    xA_size: int, n_devices: int, dtype=np.float32, planar: bool = True,
) -> int:
    """Bytes crossing the mesh per backward subgrid (analytic).

    The subgrid [xA, xA] is broadcast to every device holding facets;
    accumulators stay device-local (no further collectives).
    """
    buf = xA_size * xA_size * _itemsize(dtype, planar)
    return int(buf * (n_devices - 1))


def column_collective_bytes(
    core, n_devices: int, n_subgrids: int, direction: str = "forward",
    subgrid_size: int | None = None,
) -> int:
    """Analytic wire bytes of ONE streamed column's collectives — the
    per-stage transfer attribution the obs instrumentation stamps on
    mesh column passes (zero off-mesh, so single-device stages carry no
    phantom traffic).

    Forward: one psum of the column's [S, xM, xM] partials (ring
    all-reduce accounting, `collective_bytes_forward` per subgrid).
    Backward: the column's subgrids broadcast to every facet shard
    (`collective_bytes_backward`; requires `subgrid_size`).
    """
    if n_devices <= 1:
        return 0
    planar = core.backend == "planar"
    if direction == "forward":
        per = collective_bytes_forward(
            core.xM_size, n_devices, core.dtype, planar
        )
    elif direction == "backward":
        if subgrid_size is None:
            raise ValueError("backward direction requires subgrid_size")
        per = collective_bytes_backward(
            subgrid_size, n_devices, core.dtype, planar
        )
    else:
        raise ValueError(f"direction must be forward|backward, got {direction!r}")
    return per * n_subgrids
