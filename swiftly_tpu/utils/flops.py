"""Analytic FLOP accounting for the planar matmul-FFT pipeline.

Every compute op in the planar backend is an einsum (or elementwise op) of
statically known shape, so the FLOP count of a whole transform is exact —
no sampling or hardware counters needed. The bench reports effective
TFLOP/s and % of the chip's published peak alongside the wall-clock, which
turns `vs_baseline` (a soft single-core-numpy yardstick) into a hard
hardware-utilisation number.

Conventions: one multiply-add = 2 FLOPs; counts follow the default "4mul"
complex-product algorithm (4 real matmuls per complex matmul,
`planar_backend._cmatmul`); elementwise twiddle/phase/window multiplies are
included (6 FLOPs per complex point) but are <1% of any total.
"""

from __future__ import annotations

import os

from ..ops.planar_backend import _DIRECT_MAX, _factor

__all__ = [
    "bwd_column_pass_flops",
    "bwd_fold_flops",
    "colpass_mode",
    "column_pass_flops",
    "fft_flops",
    "forward_batched_flops",
    "forward_sampled_flops",
    "backward_batched_flops",
    "backward_sampled_flops",
    "peak_tflops",
    "resolve_colpass",
    "resolve_colpass_bwd",
    "sampled_facet_pass_flops",
]


def fft_flops(n: int, batch: int) -> int:
    """FLOPs of one planar matmul (i)FFT of size n over `batch` rows.

    Direct (n <= 1024): 4 real [batch, n] x [n, n] matmuls.
    Factored n = n1*n2: two matmul rounds (8*batch*n*(n1+n2)) plus the
    elementwise twiddle (6 per complex point).
    """
    if n <= _DIRECT_MAX:
        return 8 * batch * n * n
    n1, n2 = _factor(n)
    return 8 * batch * n * (n1 + n2) + 6 * batch * n


def colpass_mode() -> str:
    """The streamed column-pass body (einsum|fft|pallas|auto, default
    auto) — the single parser of SWIFTLY_COLPASS, shared with
    `parallel.streamed` so the FLOP shape can never silently diverge
    from the executed algorithm. Read at trace/report time."""
    mode = os.environ.get("SWIFTLY_COLPASS", "auto")
    if mode not in ("einsum", "fft", "pallas", "auto"):
        raise ValueError(
            f"SWIFTLY_COLPASS must be einsum|fft|pallas|auto, got {mode!r}"
        )
    return mode


def _pallas_colpass_available(core) -> bool:
    """The fused Pallas column pass needs the planar backend (it
    contracts split real/imaginary planes)."""
    return getattr(core, "backend", "") == "planar"


def _on_tpu() -> bool:
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


# Minimum stage-2 contraction depth (facets_in_program * m) for "auto"
# to pick the einsum FORWARD body. Measured on v5e
# (docs/performance.md): despite ~2x the chain's matmul FLOPs, the
# einsum body won at every measured forward shape — resident 32k
# (K = 9*256: 14.6 -> 12.2 s) AND facet-slab 64k (K = 1*256:
# 66.7 -> 61.7 s) — so "auto" currently resolves einsum everywhere;
# the threshold stays as the tuning point should a shallower shape
# regress.
_COLPASS_MIN_K = 0


def resolve_colpass(core, n_facets_in_program: int) -> str:
    """The column-pass body a program with `n_facets_in_program` stacked
    facets runs: the explicit SWIFTLY_COLPASS setting, or — under
    "auto" — the fused Pallas kernel on TPU (planar backend; Mosaic
    keeps the accumulator tile in VMEM across the whole K = F*m
    contraction, beating the einsum chain at every measured forward
    shape) falling back to the measured contraction-depth heuristic
    between einsum and fft elsewhere. An explicit ``pallas`` request on
    a non-planar backend degrades to einsum (there are no split planes
    to feed the kernel)."""
    mode = colpass_mode()
    if mode == "pallas":
        return "pallas" if _pallas_colpass_available(core) else "einsum"
    if mode != "auto":
        return mode
    if _pallas_colpass_available(core) and _on_tpu():
        return "pallas"
    if n_facets_in_program * core.xM_yN_size >= _COLPASS_MIN_K:
        return "einsum"
    return "fft"


def resolve_colpass_bwd(core, n_facets_in_program: int) -> str:
    """Backward column-pass body: SWIFTLY_COLPASS_BWD if set (einsum|
    fft|pallas), else the same fused Pallas kernel the forward resolves
    to on TPU (``reduce_f=False`` — per-facet Z products), einsum
    elsewhere — re-measured on v5e r5 (32k round trip, fg=2): 41.8 s
    einsum vs 48.3 s fft chain. The r4 measurement had einsum LOSING
    (80.4 vs 66.3 s), but that predated the one-shot
    `_bwd_scatter_rows` accumulator and the rebalanced Sb blocks; with
    those, the adjoint einsums' K=xM MXU contractions beat the
    per-(subgrid, facet) fft chains despite ~2x the FLOPs."""
    mode = os.environ.get("SWIFTLY_COLPASS_BWD", "")
    if mode:
        if mode not in ("einsum", "fft", "pallas"):
            raise ValueError(
                f"SWIFTLY_COLPASS_BWD must be einsum|fft|pallas, got {mode!r}"
            )
        if mode == "pallas" and not _pallas_colpass_available(core):
            return "einsum"
        return mode
    if _pallas_colpass_available(core) and _on_tpu():
        return "pallas"
    return "einsum"


def _per_subgrid_flops(
    core, subgrid_size: int, n_facets: int, colpass: str = "fft"
) -> int:
    """FLOPs to turn one column's NMBF_BFs into one finished subgrid.

    ``colpass="fft"`` (the batched path, and SWIFTLY_COLPASS=fft): per
    facet, add_to_subgrid axis 0 (fft size m over m rows) and axis 1
    (fft size m over xM rows) plus the Fn windows; then one
    finish_subgrid (ifft size xM over xM rows, crop, ifft size xM over
    xA rows, crop).

    ``colpass="einsum"``: one complex [xM, F*m] x [F*m, xM] stage-2
    contraction (4 real matmuls) — the facet reduction and the finish
    iFFTs are inside it / its operators, and the finish is a crop +
    masks. The per-program operator build (~F*(m^3 + 2*xM*m^2) complex
    ops, <0.5% of any cover) is excluded — understating, never
    overstating, the achieved TFLOP/s.

    ``colpass="pallas"``: the fused kernel runs the prepare matmul PER
    SUBGRID (dot #1 of the triple product A0 @ Xn @ B1): per facet a
    complex [xM, m] x [m, m] then [xM, m] x [m, xM] — so the hoisted
    per-column H contraction of the einsum shape moves here, at the
    gathered m-column width instead of the full yN width.
    """
    m, xM = core.xM_yN_size, core.xM_size
    if colpass == "einsum":
        return 8 * xM * xM * n_facets * m + 4 * subgrid_size**2
    if colpass == "pallas":
        return 8 * xM * m * (m + xM) * n_facets + 4 * subgrid_size**2
    per_facet = (
        fft_flops(m, m) + 6 * m * m  # axis 0 fft + Fn window
        + fft_flops(m, xM) + 6 * xM * m  # axis 1 fft + Fn window
    )
    finish = fft_flops(xM, xM) + fft_flops(xM, subgrid_size)
    # facet-sum (2 adds per complex point per facet) + masks
    reduce_mask = 2 * (n_facets - 1) * xM * xM + 4 * subgrid_size**2
    return n_facets * per_facet + finish + reduce_mask


def _column_prepare_flops(core, n_facets: int, colpass: str = "fft") -> int:
    """Axis-1 preparation of one column's rows: per facet, Fb window +
    ifft size yN over m rows; the einsum column pass adds its hoisted
    H = A0 @ NMBF_BF contraction ([xM, m] x [m, yN] complex per facet,
    shared by all the column's subgrids). The pallas body has NO hoisted
    term — its prepare matmul fuses into the per-subgrid triple product
    (counted in `_per_subgrid_flops`)."""
    m, yN = core.xM_yN_size, core.yN_size
    base = n_facets * (fft_flops(yN, m) + 6 * m * yN)
    if colpass == "einsum":
        base += n_facets * 8 * core.xM_size * m * yN
    return base


# -- per-stage counts (the obs instrumentation's attribution unit) ----------
#
# The whole-cover totals below are SUMS of these stage counts, so the
# per-stage MFU the metrics registry reports and the artifact-level
# tflops/mfu_pct the bench reports can never diverge: one formula per
# stage, used by both.


def sampled_facet_pass_flops(
    core, n_facets: int, facet_size: int, n_rows: int,
    real_facets: bool = False,
) -> int:
    """FLOPs of ONE sampled-DFT facet-pass einsum extracting `n_rows`
    contribution rows from `n_facets` resident facets (the forward's
    per-column-group dispatch; `n_rows` = G*m). ``real_facets`` halves
    the matmuls (the zero imaginary plane's einsums are skipped)."""
    yB = facet_size
    mm = 4 if real_facets else 8
    return mm * n_rows * yB * (n_facets * yB) + 6 * n_facets * n_rows * yB


def column_pass_flops(
    core, n_facets: int, n_subgrids: int, subgrid_size: int,
    colpass: str = "fft",
) -> int:
    """FLOPs of ONE forward column pass: axis-1 preparation plus the
    summation/finish of the column's `n_subgrids` subgrids, for the body
    (`colpass`) the executor actually runs."""
    return _column_prepare_flops(core, n_facets, colpass) + (
        n_subgrids * _per_subgrid_flops(core, subgrid_size, n_facets, colpass)
    )


def bwd_column_pass_flops(
    core, n_facets: int, n_subgrids: int, facet_size: int,
    subgrid_size: int, colpass: str = "einsum",
) -> int:
    """FLOPs of ONE backward column pass (subgrid column -> NAF_BMNAF
    rows): per-subgrid prepare/extract plus the per-column axis-1
    finish, for the executed body."""
    m, xM, yN = core.xM_yN_size, core.xM_size, core.yN_size
    if colpass in ("einsum", "pallas"):
        # two K=xM complex einsums per (subgrid, facet) plus the
        # scatter-add into the [F, m, yN] accumulator; the fused pallas
        # body runs the same contractions (as one grid program), so the
        # FLOP shape is identical
        per_sg = n_facets * 8 * (m * xM * xM + m * m * xM)
        per_sg += n_facets * 2 * m * yN
    else:
        # fft body: prepare (two ffts) + per-facet extraction
        per_sg = fft_flops(xM, subgrid_size) + fft_flops(xM, xM)
        per_sg += n_facets * (
            fft_flops(m, m) + 6 * m * xM + fft_flops(m, m) + 6 * m * m
        )
    col_fin = n_facets * (fft_flops(yN, m) + 6 * m * facet_size)
    return n_subgrids * per_sg + col_fin


def bwd_fold_flops(core, n_facets: int, facet_size: int, n_rows: int) -> int:
    """FLOPs of ONE adjoint sampled-DFT fold of `n_rows` concatenated
    column rows into the [F, yB, yB] image accumulator (the backward's
    per-fold-group dispatch; `n_rows` = P*m)."""
    yB = facet_size
    return 8 * n_rows * yB * (n_facets * yB) + 6 * n_facets * n_rows * yB


def forward_batched_flops(
    core, n_facets: int, facet_size: int, n_columns: int,
    subgrids_per_column: int, subgrid_size: int,
) -> int:
    """Total FLOPs of the batched whole-cover forward transform.

    prepare_facets (once) + per-column extraction/preparation + per-subgrid
    summation/finish — the exact op sequence of
    `parallel.batched.forward_all_batch`.
    """
    yN = core.yN_size
    prepare = n_facets * (fft_flops(yN, facet_size) + 6 * facet_size * yN)
    columns = n_columns * _column_prepare_flops(core, n_facets)
    subgrids = (
        n_columns
        * subgrids_per_column
        * _per_subgrid_flops(core, subgrid_size, n_facets)
    )
    return prepare + columns + subgrids


def forward_sampled_flops(
    core, n_facets: int, facet_size: int, n_columns: int,
    subgrids_per_column: int, subgrid_size: int,
    real_facets: bool = False, finish_passes: int = 1,
    colpass: str | None = None,
) -> int:
    """Total FLOPs of the streamed device-resident (sampled-DFT) forward.

    Facet pass: one [R, yB] x [F*yB, yB] complex matmul with R = C*m
    sampled rows, plus the per-facet diagonal phase; column pass: same as
    the batched path's per-column work.

    ``real_facets``: the facets' imaginary plane is identically zero, so
    the sampled matmul is 2 real matmuls instead of 4 — HALF the facet
    pass FLOPs (honest accounting: work skipped is not work done).
    ``finish_passes``: the facet-slab-streamed path finishes each subgrid
    once per slab and sums (linearity) — count the repeats.
    """
    yB = facet_size
    m, xM = core.xM_yN_size, core.xM_size
    if colpass is None:
        colpass = resolve_colpass(core, n_facets)
    R = n_columns * m
    facet_pass = sampled_facet_pass_flops(
        core, n_facets, yB, R, real_facets=real_facets
    )
    columns = n_columns * _column_prepare_flops(core, n_facets, colpass)
    subgrids = (
        n_columns
        * subgrids_per_column
        * _per_subgrid_flops(core, subgrid_size, n_facets, colpass)
    )
    if colpass in ("einsum", "pallas"):
        extra_finish = 0  # slab finish is a crop: no repeated iFFT passes
    else:
        extra_finish = (
            (finish_passes - 1)
            * n_columns
            * subgrids_per_column
            * (fft_flops(xM, xM) + fft_flops(xM, subgrid_size)
               + 4 * subgrid_size**2)
        )
    return facet_pass + columns + subgrids + extra_finish


def backward_sampled_flops(
    core, n_facets: int, facet_size: int, n_columns: int,
    subgrids_per_column: int, subgrid_size: int,
    colpass: str | None = None,
) -> int:
    """Total FLOPs of the streamed sampled-residency backward transform.

    Column stage per subgrid (prepare + per-facet extract) and per-column
    axis-1 finish as in the batched path; the axis-0 facet pass is the
    adjoint sampled einsum: [R, yB_i]^T x [F, R, yB_j] over all R =
    n_columns*m rows, plus conjugate phases and the Fb weighting.
    """
    m = core.xM_yN_size
    yB = facet_size
    if colpass is None:
        colpass = resolve_colpass_bwd(core, n_facets)
    columns = n_columns * bwd_column_pass_flops(
        core, n_facets, subgrids_per_column, yB, subgrid_size, colpass
    )
    fold = bwd_fold_flops(core, n_facets, yB, n_columns * m)
    finish_mask = 2 * n_facets * yB * yB
    return columns + fold + finish_mask


def backward_batched_flops(
    core, n_facets: int, facet_size: int, n_columns: int,
    subgrids_per_column: int, subgrid_size: int,
) -> int:
    """Total FLOPs of the batched whole-cover backward transform.

    Per subgrid: prepare_subgrid (two ffts) + per-facet extraction (two
    iffts + Fn windows); per column: per-facet axis-1 finish
    (fft size yN over m rows) + Fb window; finish: per-facet axis-0
    finish (fft size yN over yB rows).
    """
    m, xM, yN = core.xM_yN_size, core.xM_size, core.yN_size
    prep = fft_flops(xM, subgrid_size) + fft_flops(xM, xM)
    extract = n_facets * (
        fft_flops(m, m) + 6 * m * xM + fft_flops(m, m) + 6 * m * m
    )
    per_sg = prep + extract
    col_fin = n_facets * (
        fft_flops(yN, m) + 6 * m * facet_size
    )
    facet_fin = n_facets * (
        fft_flops(yN, facet_size) + 6 * facet_size * yN
    )
    return (
        n_columns * subgrids_per_column * per_sg
        + n_columns * col_fin
        + facet_fin
    )


# Published peak dense-matmul throughput, TFLOP/s. The planar pipeline runs
# f32 einsums at Precision.HIGHEST (bf16x3/f32 accumulate on the MXU), so
# the honest utilisation ceiling on TPU is the bf16 MXU peak divided by the
# 3 bf16 passes HIGHEST costs; published bf16 peaks below.
_PEAKS_BF16 = {
    "TPU v5 lite": 197.0,  # v5e
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v4": 275.0,
    "TPU v6e": 918.0,
}


def peak_tflops(device=None) -> float | None:
    """Peak f32-HIGHEST matmul TFLOP/s for the current device, or None.

    Override with SWIFTLY_PEAK_TFLOPS (e.g. from a measured matmul
    roofline) when the device is not in the table.
    """
    env = os.environ.get("SWIFTLY_PEAK_TFLOPS")
    if env:
        return float(env)
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for name, bf16 in _PEAKS_BF16.items():
        if name.lower() in str(kind).lower():
            return bf16 / 3.0  # HIGHEST = 3 bf16 MXU passes
    return None
