"""Planar-complex backend: complex arrays as (..., 2) real pairs + MXU FFT.

TPU hardware (and this environment's TPU runtime in particular) has no
complex dtypes and no XLA FFT op. This backend represents every complex
array as a real array with a trailing length-2 axis (re, im) and implements
the centred FFT as matmuls against precomputed DFT/twiddle factors — the
four-step Cooley-Tukey factorisation n = n1*n2 that maps the FLOPs onto the
MXU (cf. "Large-Scale Discrete Fourier Transform on TPUs",
arxiv.org/abs/2002.03260; see PAPERS.md).

The module implements the same L0 namespace protocol as
:mod:`swiftly_tpu.ops.primitives` (`ndim`, `broadcast_along`, `pad_mid`,
`extract_mid`, `fft`, `ifft`, `roll_axis`, `wrapped_extract`,
`wrapped_embed`), so the SwiftlyCore math functions run on it unchanged.
Window vectors (Fb/Fn) stay real 1D and broadcast over both planes.

Precision: float32 planar by default on TPU (relative accuracy ~1e-6 per
transform); float64 planar under x64 for exactness tests on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "broadcast_along",
    "extract_mid",
    "fft",
    "from_planar",
    "ifft",
    "ndim",
    "pad_mid",
    "roll_axis",
    "to_planar",
    "wrapped_extract",
    "wrapped_embed",
]

# Largest size transformed by a single direct DFT matmul; larger sizes are
# factored n = n1*n2 with both factors <= this.
_DIRECT_MAX = 1024


def to_planar(a, dtype=jnp.float32):
    """Convert a complex array to planar (..., 2) real representation."""
    a = np.asarray(a)
    return jnp.asarray(
        np.stack([a.real, a.imag], axis=-1), dtype=dtype
    )


def from_planar(a) -> np.ndarray:
    """Convert a planar (..., 2) array back to a numpy complex array."""
    a = np.asarray(a)
    return a[..., 0] + 1j * a[..., 1]


def ndim(a) -> int:
    """Logical (complex) dimensionality: the trailing re/im axis is not a
    data dimension."""
    return a.ndim - 1


def broadcast_along(vec, ndim: int, axis: int):
    """Reshape a real 1D window so it broadcasts along logical `axis` and
    over both re/im planes."""
    shape = [1] * (ndim + 1)
    shape[axis] = -1
    return jnp.reshape(vec, shape)


def pad_mid(a, n: int, axis: int):
    n0 = a.shape[axis]
    if n == n0:
        return a
    before = n // 2 - n0 // 2
    pads = [(0, 0)] * a.ndim
    pads[axis] = (before, n - n0 - before)
    return jnp.pad(a, pads)


def extract_mid(a, n: int, axis: int):
    n0 = a.shape[axis]
    if n == n0:
        return a
    start = n0 // 2 - n // 2
    sl = [slice(None)] * a.ndim
    sl[axis] = slice(start, start + n)
    return a[tuple(sl)]


def roll_axis(a, shift, axis: int):
    return jnp.roll(a, shift, axis=axis)


# Axis- and dtype-generic, so the planar trailing (re, im) axis needs no
# special handling: share the complex backend's implementations.
from .primitives import wrapped_embed, wrapped_extract  # noqa: E402


# ---------------------------------------------------------------------------
# Matmul FFT
# ---------------------------------------------------------------------------


def _factor(n: int):
    """Split n = n1*n2 with both factors <= _DIRECT_MAX, taking the
    LARGEST valid n1 (smallest n2): the n1-sized DFT matmul carries the
    FLOPs, so big-n1 splits keep the contraction long and MXU-friendly."""
    best = None
    for n2 in range(2, int(np.sqrt(n)) + 1):
        if n % n2 == 0:
            n1 = n // n2
            if n1 <= _DIRECT_MAX:
                best = (n1, n2)
                break
    if best is None:
        raise ValueError(
            f"FFT size {n} cannot be factored into factors <= {_DIRECT_MAX}"
        )
    return best


@functools.lru_cache(maxsize=None)
def _dft_matrix(n: int, sign: int, centred: bool) -> tuple[np.ndarray, np.ndarray]:
    """(re, im) of the DFT matrix, float64.

    With `centred`, the fftshift/ifftshift index shifts and (for the
    inverse) the 1/n scale are folded into the matrix, so a centred
    transform is the bare matmul: W[j, k] = exp(sign*2πi (j-c)(k-c)/n)/s
    with c = n//2, s = n if sign > 0 else 1.
    """
    idx = np.arange(n) - (n // 2 if centred else 0)
    w = np.exp(sign * 2j * np.pi * np.outer(idx, idx % n) / n)
    if centred and sign > 0:
        w = w / n
    return np.ascontiguousarray(w.real), np.ascontiguousarray(w.imag)


@functools.lru_cache(maxsize=None)
def _twiddle(n1: int, n2: int, sign: int) -> tuple[np.ndarray, np.ndarray]:
    """(re, im) of T[k1, i2] = exp(sign*2πi k1 i2/(n1 n2)), float64.

    The inverse transform's 1/n scale is folded in here (elementwise, so
    it is free)."""
    k1i2 = np.outer(np.arange(n1), np.arange(n2))
    t = np.exp(sign * 2j * np.pi * k1i2 / (n1 * n2))
    if sign > 0:
        t = t / (n1 * n2)
    return np.ascontiguousarray(t.real), np.ascontiguousarray(t.imag)


import os


# TPU matmuls default to bfloat16 multiplications, which destroys FFT
# accuracy (~1e-3 relative). HIGHEST forces full-f32 products (multi-pass
# bf16 on the MXU) and recovers ~1e-7 relative error at f32; HIGH costs
# half of HIGHEST's MXU passes for ~1e-6 relative — inside this
# pipeline's accuracy budget at f32, worth ~2x on einsum-bound stages.
def matmul_precision():
    """Einsum precision for the planar pipeline (read at TRACE time —
    like SWIFTLY_CMATMUL, set SWIFTLY_PRECISION before the first
    transform runs; highest|high|default)."""
    name = os.environ.get("SWIFTLY_PRECISION", "highest").lower()
    if name not in ("default", "high", "highest"):
        raise ValueError(
            f"SWIFTLY_PRECISION must be default|high|highest, got {name!r}"
        )
    return getattr(jax.lax.Precision, name.upper())


def _cmatmul_algo() -> str:
    """Complex-product algorithm: "karatsuba" (3 real matmuls, ~25% faster,
    ~2x rounding error at f32) or "4mul" (4 real matmuls, most accurate).

    Read at TRACE time: jitted programs bake in whichever algorithm was
    active when they first compiled, and the jit cache ignores later
    changes — set the env var before any transform runs (eager/new-shape
    calls do re-read it, which is how the unit tests toggle it)."""
    algo = os.environ.get("SWIFTLY_CMATMUL", "4mul")
    if algo not in ("4mul", "karatsuba"):
        raise ValueError(f"SWIFTLY_CMATMUL must be 4mul|karatsuba, got {algo!r}")
    return algo


def _cmatmul(zr, zi, w, spec, dtype):
    """Complex contraction via real einsums (MXU path).

    Default "4mul": four K-length real products — kept separate rather
    than one [2K, 2N] block matmul, whose 2K-length accumulation
    measurably costs ~2x accuracy at f32. "karatsuba" trades ~2x f32
    rounding error for 3 products:
      k1 = (zr+zi)·wr, k2 = zi·(wr+wi), k3 = zr·(wi-wr)
      re = k1 - k2,  im = k1 + k3
    (matrix sums are compile-time constants, folded once per program)."""
    wr = jnp.asarray(w[0], dtype=dtype)
    wi = jnp.asarray(w[1], dtype=dtype)
    prec = matmul_precision()
    f = lambda a, b: jnp.einsum(spec, a, b, precision=prec)
    if _cmatmul_algo() == "karatsuba":
        k1 = f(zr + zi, wr)
        k2 = f(zi, wr + wi)
        k3 = f(zr, wi - wr)
        return k1 - k2, k1 + k3
    rr = f(zr, wr)
    ii = f(zi, wi)
    ri = f(zr, wi)
    ir = f(zi, wr)
    return rr - ii, ri + ir


def _fft_direct_centred(z, sign: int):
    """Centred DFT along the second-to-last axis of planar z (..., n, 2):
    a single round of matmuls (shifts and inverse scale live in the
    matrix). With SWIFTLY_PALLAS=1 the four real products run as one
    fused Pallas kernel (see ops/pallas_kernels.py)."""
    n = z.shape[-2]
    w = _dft_matrix(n, sign, True)
    from .pallas_kernels import cmatmul_pallas, pallas_enabled

    if pallas_enabled():
        lead = z.shape[:-2]
        zr = z[..., 0].reshape(-1, n)
        zi = z[..., 1].reshape(-1, n)
        outr, outi = cmatmul_pallas(
            zr, zi,
            jnp.asarray(w[0], z.dtype), jnp.asarray(w[1], z.dtype),
        )
        return jnp.stack([outr, outi], axis=-1).reshape(lead + (n, 2))
    outr, outi = _cmatmul(z[..., 0], z[..., 1], w, "...i,ik->...k", z.dtype)
    return jnp.stack([outr, outi], axis=-1)


def _fft_factored(z, sign: int):
    """Uncentred DFT (four-step n = n1*n2) along the second-to-last axis
    of planar z; the inverse 1/n scale is folded into the twiddle."""
    n = z.shape[-2]
    rdt = z.dtype
    n1, n2 = _factor(n)
    # i = i2 + n2*i1: reshape splits index into (i1, i2) row-major
    zr = z[..., 0].reshape(z.shape[:-2] + (n1, n2))
    zi = z[..., 1].reshape(z.shape[:-2] + (n1, n2))

    # Step 1: DFT over i1 -> (..., k1, i2)
    ar, ai = _cmatmul(
        zr, zi, _dft_matrix(n1, sign, False), "...ij,ik->...kj", rdt
    )

    # Step 2: twiddle T[k1, i2] (elementwise)
    tr, ti = _twiddle(n1, n2, sign)
    tr = jnp.asarray(tr, dtype=rdt)
    ti = jnp.asarray(ti, dtype=rdt)
    br = ar * tr - ai * ti
    bi = ar * ti + ai * tr

    # Step 3: DFT over i2 -> (..., k1, k2)
    cr, ci = _cmatmul(
        br, bi, _dft_matrix(n2, sign, False), "...kj,jl->...kl", rdt
    )

    # Output index k = k1 + n1*k2 -> lay out as (k2, k1) then flatten
    cr = jnp.swapaxes(cr, -2, -1).reshape(cr.shape[:-2] + (n,))
    ci = jnp.swapaxes(ci, -2, -1).reshape(ci.shape[:-2] + (n,))
    return jnp.stack([cr, ci], axis=-1)


def _fft_centred(a, axis: int, sign: int):
    n = a.shape[axis]
    z = jnp.moveaxis(a, axis, -2)
    if n <= _DIRECT_MAX:
        z = _fft_direct_centred(z, sign)
    else:
        z = jnp.roll(z, -(n // 2), axis=-2)  # ifftshift
        z = _fft_factored(z, sign)
        z = jnp.roll(z, n // 2, axis=-2)  # fftshift
    return jnp.moveaxis(z, -2, axis)


def fft(a, axis: int):
    """Centred-zero FFT along logical `axis` of a planar array."""
    return _fft_centred(a, axis, -1)


def ifft(a, axis: int):
    """Centred-zero inverse FFT along logical `axis` of a planar array."""
    return _fft_centred(a, axis, +1)
