"""Analytic source-model oracle and mask generation (host-side, numpy).

The universal test oracle of the framework: facets are built by placing
point sources on an integer pixel grid (mod N), subgrids by evaluating the
direct Fourier sum of the same sources. Every numerical claim the framework
makes is checked against these. Behavioural parity with the reference
(/root/reference/src/ska_sdp_exec_swiftly/fourier_transform/
fourier_algorithm.py:218-344), written independently and vectorised.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "generate_masks",
    "make_facet_from_sources",
    "make_real_facet_plane_from_sources",
    "make_sparse_real_facet_from_sources",
    "make_subgrid_from_sources",
    "mask_from_slices",
    "SparseRealFacet",
]


def make_facet_from_sources(
    sources,
    image_size: int,
    facet_size: int,
    facet_offsets,
    facet_masks=None,
):
    """Build a facet (image-space chunk) from a point-source list.

    Each source is an ``(intensity, *coords)`` tuple with integer image
    coordinates relative to the image centre; coordinates wrap modulo
    `image_size`. The number of offsets determines the dimensionality.
    """
    ndim = len(facet_offsets)
    facet = np.zeros(ndim * (facet_size,), dtype=complex)
    centre_of_facet = np.asarray(facet_offsets, dtype=int) - facet_size // 2

    for intensity, *coords in sources:
        if len(coords) != ndim:
            raise ValueError(
                f"Source has {len(coords)} coordinates, expected {ndim}"
            )
        rel = np.mod(np.asarray(coords, dtype=int) - centre_of_facet, image_size)
        if np.all((rel >= 0) & (rel < facet_size)):
            facet[tuple(rel)] += intensity

    for axis, mask in enumerate(facet_masks or []):
        if mask is not None:
            shape = [1] * ndim
            shape[axis] = -1
            facet = facet * np.reshape(np.asarray(mask), shape)
    return facet


def make_real_facet_plane_from_sources(
    sources,
    image_size: int,
    facet_size: int,
    facet_offsets,
    facet_masks=None,
    dtype=np.float32,
):
    """`make_facet_from_sources` as a real plane, sparse-aware.

    Point-source facets are real and almost entirely zero: the dense
    complex build (`make_facet_from_sources`) allocates and mask-scans
    the full facet_size**ndim complex array (8 GB per facet at 64k),
    while the result is just zeros plus <= len(sources) scaled pixels.
    This builds exactly that: a zeroed real array written pointwise, with
    each hit pixel scaled by its per-axis mask values. Equal to
    `make_facet_from_sources(...).real` (pinned by tests); intended for
    the large-N streamed drivers whose real-plane fast path wants this
    layout anyway.
    """
    ndim = len(facet_offsets)
    facet = np.zeros(ndim * (facet_size,), dtype=dtype)
    centre_of_facet = np.asarray(facet_offsets, dtype=int) - facet_size // 2
    masks = [
        None if m is None else np.asarray(m)
        for m in (facet_masks or [None] * ndim)
    ]

    for intensity, *coords in sources:
        if len(coords) != ndim:
            raise ValueError(
                f"Source has {len(coords)} coordinates, expected {ndim}"
            )
        rel = np.mod(
            np.asarray(coords, dtype=int) - centre_of_facet, image_size
        )
        if np.all((rel >= 0) & (rel < facet_size)):
            scale = float(intensity)
            for axis, mask in enumerate(masks):
                if mask is not None:
                    scale *= float(mask[rel[axis]])
            facet[tuple(rel)] += scale
    return facet


class SparseRealFacet:
    """A real facet plane as coordinates + values: zeros plus a few
    pixels.

    Point-source facet models (the reference's
    ``make_facet_from_sources`` input path) are almost entirely zero —
    at 64k one dense real plane is 2 GB, but the information content is
    a handful of mask-scaled pixels. This descriptor carries exactly
    those, so streamed executors can SYNTHESISE the dense plane on
    device (a scatter into zeros) instead of uploading gigabytes per
    facet slab — decisive on tunnel-attached runtimes where h2d
    bandwidth, not compute, bounds facet-slab streaming. The transform
    itself still runs densely; only the input transport is sparse.
    """

    def __init__(self, size, rows, cols, vals):
        self.size = int(size)
        self.rows = np.asarray(rows, dtype=np.int32)
        self.cols = np.asarray(cols, dtype=np.int32)
        self.vals = np.asarray(vals)
        if not (len(self.rows) == len(self.cols) == len(self.vals)):
            raise ValueError("rows/cols/vals must have equal length")

    @property
    def n_pixels(self):
        return len(self.vals)

    def densify(self, dtype=None):
        """The equivalent dense real plane (duplicates accumulate)."""
        out = np.zeros(
            (self.size, self.size), dtype=dtype or self.vals.dtype
        )
        np.add.at(out, (self.rows, self.cols), self.vals)
        return out


def make_sparse_real_facet_from_sources(
    sources,
    image_size: int,
    facet_size: int,
    facet_offsets,
    facet_masks=None,
    dtype=np.float32,
):
    """`make_real_facet_plane_from_sources` as a `SparseRealFacet`.

    Identical pixel/mask math (densify() equals the dense builder,
    pinned by tests); 2D only — the streamed executors that consume it
    are 2D."""
    if len(facet_offsets) != 2:
        raise ValueError("sparse facets are 2D (two offsets required)")
    centre = np.asarray(facet_offsets, dtype=int) - facet_size // 2
    masks = [
        None if m is None else np.asarray(m)
        for m in (facet_masks or [None, None])
    ]
    rows, cols, vals = [], [], []
    for intensity, *coords in sources:
        if len(coords) != 2:
            raise ValueError(
                f"Source has {len(coords)} coordinates, expected 2"
            )
        rel = np.mod(np.asarray(coords, dtype=int) - centre, image_size)
        if np.all((rel >= 0) & (rel < facet_size)):
            scale = float(intensity)
            for axis, mask in enumerate(masks):
                if mask is not None:
                    scale *= float(mask[rel[axis]])
            rows.append(int(rel[0]))
            cols.append(int(rel[1]))
            vals.append(scale)
    return SparseRealFacet(
        facet_size, rows, cols, np.asarray(vals, dtype=dtype)
    )


def make_subgrid_from_sources(
    sources,
    image_size: int,
    subgrid_size: int,
    subgrid_offsets,
    subgrid_masks=None,
):
    """Build a subgrid (grid-space chunk) by direct Fourier transform.

    Exact DFT of the point-source model, normalised by image_size per
    dimension. The expensive-but-exact ground truth.
    """
    ndim = len(subgrid_offsets)
    # Per-axis uv coordinate ranges centred on each subgrid offset
    axes_uv = [
        np.arange(off - subgrid_size // 2, off + (subgrid_size + 1) // 2)
        for off in subgrid_offsets
    ]
    subgrid = np.zeros(ndim * (subgrid_size,), dtype=complex)
    for intensity, *coords in sources:
        if len(coords) != ndim:
            raise ValueError(
                f"Source has {len(coords)} coordinates, expected {ndim}"
            )
        term = np.asarray(intensity / image_size**ndim, dtype=complex)
        # Separable phase factors: exp(2πi u_d x_d / N) outer-multiplied
        for axis, (uv, x) in enumerate(zip(axes_uv, coords)):
            phase = np.exp((2j * np.pi / image_size) * uv * x)
            shape = [1] * ndim
            shape[axis] = -1
            term = term * np.reshape(phase, shape)
        subgrid += term

    for axis, mask in enumerate(subgrid_masks or []):
        if mask is not None:
            shape = [1] * ndim
            shape[axis] = -1
            subgrid = subgrid * np.reshape(np.asarray(mask), shape)
    return subgrid


def generate_masks(image_size: int, mask_size: int, offsets) -> np.ndarray:
    """Per-offset 0/1 ownership masks for a 1D cover.

    Boundaries between consecutive chunks sit at the midpoint of their
    offsets (wrapping at image_size), so every image pixel belongs to
    exactly one chunk. Parity: reference ``generate_masks``
    (``fourier_algorithm.py:318-344``).
    """
    offsets = np.asarray(offsets)
    nxt = np.concatenate([offsets[1:], [image_size + offsets[0]]])
    border = (offsets + nxt) // 2
    masks = np.zeros((len(offsets), mask_size), dtype=int)
    for i, off in enumerate(offsets):
        left = border[i - 1] - off + mask_size // 2
        if i == 0:
            # row 0's left border wraps around the image
            left %= image_size
        right = border[i] - off + mask_size // 2
        if left < 0 or right > mask_size:
            raise ValueError(
                "Mask size too small to cover this facet/subgrid layout"
            )
        masks[i, left:right] = 1
    return masks


def mask_from_slices(slice_list, mask_size: int) -> np.ndarray:
    """Realise a 0/1 mask from a list of slices (sparse mask storage)."""
    mask = np.zeros((mask_size,))
    for sl in slice_list:
        mask[sl] = 1
    return mask
