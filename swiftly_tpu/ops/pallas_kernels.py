"""Pallas TPU kernels for the planar-complex hot ops.

The planar backend's dominant op is the complex DFT matmul: four real
[B, K] x [K, N] products combined as (rr - ii, ri + ir)
(`planar_backend._cmatmul`). As separate XLA einsums each z block is
streamed from HBM up to four times; this kernel tiles the four products
into one grid program that reads each (z, w) block pair once per output
tile and keeps both accumulators in VMEM — an HBM-bandwidth optimisation
of exactly the kind the reference delegates to its native C library
(/root/reference/src/ska_sdp_exec_swiftly/fourier_transform/core.py:487-929,
the `ska-sdp-func` fast path).

Usage is opt-in (``SWIFTLY_PALLAS=1``): correctness is validated in
interpreter mode on any backend (tests/test_pallas.py), but this
environment's remote-compile TPU relay cannot compile Mosaic kernels, so
the default planar path stays on plain XLA einsums.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["cmatmul_pallas", "pallas_enabled"]


def pallas_enabled() -> bool:
    """True when the Pallas fast path is requested via SWIFTLY_PALLAS=1."""
    return os.environ.get("SWIFTLY_PALLAS", "0") == "1"


def _kernel(zr_ref, zi_ref, wr_ref, wi_ref, or_ref, oi_ref):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        or_ref[...] = jnp.zeros_like(or_ref)
        oi_ref[...] = jnp.zeros_like(oi_ref)

    zr = zr_ref[...]
    zi = zi_ref[...]
    wr = wr_ref[...]
    wi = wi_ref[...]
    # HIGHEST matches the einsum path: default bf16 MXU passes would
    # degrade the FFT to ~1e-3 relative error (see planar_backend.matmul_precision).
    dot = functools.partial(
        jnp.dot,
        preferred_element_type=or_ref.dtype,
        precision=jax.lax.Precision.HIGHEST,
    )
    or_ref[...] += dot(zr, wr) - dot(zi, wi)
    oi_ref[...] += dot(zr, wi) + dot(zi, wr)


def _pad_to(a, mult, axis):
    n = a.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return a
    pads = [(0, 0)] * a.ndim
    pads[axis] = (0, rem)
    return jnp.pad(a, pads)


@functools.partial(
    jax.jit, static_argnames=("bm", "bn", "bk", "interpret")
)
def cmatmul_pallas(zr, zi, wr, wi, *, bm=256, bn=256, bk=256,
                   interpret=False):
    """(zr + i zi) @ (wr + i wi) -> (out_r, out_i), fused on the MXU.

    :param zr, zi: [B, K] real/imaginary planes of the batched vectors
    :param wr, wi: [K, N] real/imaginary planes of the DFT matrix
    :param bm, bn, bk: tile sizes (batch, output, contraction)
    :param interpret: run in the Pallas interpreter (any backend)
    """
    B, K = zr.shape
    _, N = wr.shape
    bm, bn, bk = min(bm, B), min(bn, N), min(bk, K)

    zr_p = _pad_to(_pad_to(zr, bm, 0), bk, 1)
    zi_p = _pad_to(_pad_to(zi, bm, 0), bk, 1)
    wr_p = _pad_to(_pad_to(wr, bk, 0), bn, 1)
    wi_p = _pad_to(_pad_to(wi, bk, 0), bn, 1)
    Bp, Kp = zr_p.shape
    _, Np = wr_p.shape

    grid = (Bp // bm, Np // bn, Kp // bk)
    z_spec = pl.BlockSpec((bm, bk), lambda i, j, k: (i, k))
    w_spec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
    o_spec = pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))
    out_shape = jax.ShapeDtypeStruct((Bp, Np), zr.dtype)

    outr, outi = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[z_spec, z_spec, w_spec, w_spec],
        out_specs=[o_spec, o_spec],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(zr_p, zi_p, wr_p, wi_p)
    return outr[:B, :N], outi[:B, :N]
