"""Pallas TPU kernels for the planar-complex hot ops.

The planar backend's dominant op is the complex DFT matmul: four real
[B, K] x [K, N] products combined as (rr - ii, ri + ir)
(`planar_backend._cmatmul`). As separate XLA einsums each z block is
streamed from HBM up to four times; this kernel tiles the four products
into one grid program that reads each (z, w) block pair once per output
tile and keeps both accumulators in VMEM — an HBM-bandwidth optimisation
of exactly the kind the reference delegates to its native C library
(/root/reference/src/ska_sdp_exec_swiftly/fourier_transform/core.py:487-929,
the `ska-sdp-func` fast path).

The second kernel, `bwd_fold_pallas`, fuses the streamed backward's
adjoint sampled fold (`parallel.streamed._bwd_sampled_fold_fn`): per
output-row block the fold runs TWO phase-matrix matmuls, a row-weight
scale, and an accumulate into the image accumulator — as XLA einsums
the accumulator block and both row planes stream through HBM once per
product. The kernel keeps the accumulator block in VMEM across the
whole contraction grid (initialised from the incoming block, scaled
partial products added in place), so each (rows, acc) block pair is
read once per output tile — the hot loop the reference delegates to
its native ``ska-sdp-func`` library, here as one Mosaic grid program.

The third kernel, `colpass_pallas`, fuses the forward/backward column
pass (`parallel.streamed._colpass_einsum_body` and the backward column
body): the prepare matmul, the K = F·m operator contraction, and the
complex recombination of each subgrid run as one grid program with the
output tile resident in VMEM across the facet × contraction sweep, so
the [F, xM, yN] prepared-facet transient of the einsum chain never
touches HBM. One kernel serves the forward body, the adjoint body, and
both shard-local variants under the mesh engine (``reduce_f`` flips
between the facet-summed forward product and the per-facet backward
product). Selected via ``SWIFTLY_COLPASS=pallas`` (or ``auto`` on TPU).

Usage is opt-in (``SWIFTLY_PALLAS=1``): correctness is validated in
interpreter mode on any backend (tests/test_pallas.py), but this
environment's remote-compile TPU relay cannot compile Mosaic kernels, so
the default planar path stays on plain XLA einsums.
``SWIFTLY_PALLAS_INTERPRET=1`` additionally forces the Pallas
interpreter at trace time — the CPU-tier escape hatch that lets the
full fold path run (and be equivalence-tested) without Mosaic.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["bwd_fold_pallas", "cmatmul_pallas", "colpass_pallas",
           "pallas_enabled", "pallas_interpret"]


def pallas_enabled() -> bool:
    """True when the Pallas fast path is requested via SWIFTLY_PALLAS=1."""
    return os.environ.get("SWIFTLY_PALLAS", "0") == "1"


def pallas_interpret() -> bool:
    """True when SWIFTLY_PALLAS_INTERPRET=1 asks for interpreter-mode
    Pallas (any backend; used by the CPU tier-1 equivalence tests)."""
    return os.environ.get("SWIFTLY_PALLAS_INTERPRET", "0") == "1"


def _kernel(zr_ref, zi_ref, wr_ref, wi_ref, or_ref, oi_ref):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        or_ref[...] = jnp.zeros_like(or_ref)
        oi_ref[...] = jnp.zeros_like(oi_ref)

    zr = zr_ref[...]
    zi = zi_ref[...]
    wr = wr_ref[...]
    wi = wi_ref[...]
    # HIGHEST matches the einsum path: default bf16 MXU passes would
    # degrade the FFT to ~1e-3 relative error (see planar_backend.matmul_precision).
    dot = functools.partial(
        jnp.dot,
        preferred_element_type=or_ref.dtype,
        precision=jax.lax.Precision.HIGHEST,
    )
    or_ref[...] += dot(zr, wr) - dot(zi, wi)
    oi_ref[...] += dot(zr, wi) + dot(zi, wr)


def _pad_to(a, mult, axis):
    n = a.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return a
    pads = [(0, 0)] * a.ndim
    pads[axis] = (0, rem)
    return jnp.pad(a, pads)


@functools.partial(
    jax.jit, static_argnames=("bm", "bn", "bk", "interpret")
)
def cmatmul_pallas(zr, zi, wr, wi, *, bm=256, bn=256, bk=256,
                   interpret=False):
    """(zr + i zi) @ (wr + i wi) -> (out_r, out_i), fused on the MXU.

    :param zr, zi: [B, K] real/imaginary planes of the batched vectors
    :param wr, wi: [K, N] real/imaginary planes of the DFT matrix
    :param bm, bn, bk: tile sizes (batch, output, contraction)
    :param interpret: run in the Pallas interpreter (any backend)
    """
    B, K = zr.shape
    _, N = wr.shape
    bm, bn, bk = min(bm, B), min(bn, N), min(bk, K)

    zr_p = _pad_to(_pad_to(zr, bm, 0), bk, 1)
    zi_p = _pad_to(_pad_to(zi, bm, 0), bk, 1)
    wr_p = _pad_to(_pad_to(wr, bk, 0), bn, 1)
    wi_p = _pad_to(_pad_to(wi, bk, 0), bn, 1)
    Bp, Kp = zr_p.shape
    _, Np = wr_p.shape

    grid = (Bp // bm, Np // bn, Kp // bk)
    z_spec = pl.BlockSpec((bm, bk), lambda i, j, k: (i, k))
    w_spec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
    o_spec = pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))
    out_shape = jax.ShapeDtypeStruct((Bp, Np), zr.dtype)

    outr, outi = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[z_spec, z_spec, w_spec, w_spec],
        out_specs=[o_spec, o_spec],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(zr_p, zi_p, wr_p, wi_p)
    return outr[:B, :N], outi[:B, :N]


def _fold_kernel(ar_ref, ai_ref, bc_ref, bs_ref, rr_ref, ri_ref, w_ref,
                 or_ref, oi_ref):
    """One adjoint-fold output tile: out = acc + w * (Bcᵀ@Rr + Bsᵀ@Ri,
    Bcᵀ@Ri − Bsᵀ@Rr). The accumulator tile loads into VMEM once (k==0)
    and every contraction step's weighted partial product adds in place
    — no HBM round trip per product, which is the whole point."""

    @pl.when(pl.program_id(2) == 0)
    def _init():
        or_ref[...] = ar_ref[...]
        oi_ref[...] = ai_ref[...]

    bc = bc_ref[...]  # [bk, bm] block of the phase matrix
    bs = bs_ref[...]
    rr = rr_ref[...]  # [bk, bn] block of the rotated row planes
    ri = ri_ref[...]
    w = w_ref[...]    # [bm, 1] row weights (Fb window x keep mask)
    # contract over axis 0 of BOTH operands (the fold's "r" index);
    # HIGHEST matches the einsum fold's matmul_precision default
    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=or_ref.dtype,
        precision=jax.lax.Precision.HIGHEST,
    )
    or_ref[...] += w * (dot(bc, rr) + dot(bs, ri))
    oi_ref[...] += w * (dot(bc, ri) - dot(bs, rr))


@functools.partial(
    jax.jit, static_argnames=("bm", "bn", "bk", "interpret")
)
def bwd_fold_pallas(acc_r, acc_i, bc, bs, rr, ri, w, *, bm=256, bn=256,
                    bk=256, interpret=False):
    """Fused adjoint-fold block: acc + w ⊙ ((Bc − i·Bs)ᵀ @ (Rr + i·Ri)).

    The planar sampled fold's per-block einsum pair plus accumulate as
    ONE grid program (see `parallel.streamed._bwd_sampled_fold_fn`'s
    Pallas body, which flattens the facet axis into the j axis before
    calling here).

    :param acc_r, acc_i: [B, J] accumulator planes (the current block)
    :param bc, bs: [R, B] adjoint DFT phase planes for the block's
        output rows (cos/sin of −kt·i)
    :param rr, ri: [R, J] phase-rotated row planes (facet axis folded
        into J)
    :param w: [B, 1] per-output-row weight (Fb window × keep mask)
    :param bm, bn, bk: tile sizes (rows, output, contraction)
    :param interpret: run in the Pallas interpreter (any backend)
    """
    B, J = acc_r.shape
    R = bc.shape[0]
    bm, bn, bk = min(bm, B), min(bn, J), min(bk, R)

    ar_p = _pad_to(_pad_to(acc_r, bm, 0), bn, 1)
    ai_p = _pad_to(_pad_to(acc_i, bm, 0), bn, 1)
    bc_p = _pad_to(_pad_to(bc, bk, 0), bm, 1)
    bs_p = _pad_to(_pad_to(bs, bk, 0), bm, 1)
    rr_p = _pad_to(_pad_to(rr, bk, 0), bn, 1)
    ri_p = _pad_to(_pad_to(ri, bk, 0), bn, 1)
    w_p = _pad_to(w, bm, 0)
    Bp, Jp = ar_p.shape
    Rp = bc_p.shape[0]

    grid = (Bp // bm, Jp // bn, Rp // bk)
    a_spec = pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))
    b_spec = pl.BlockSpec((bk, bm), lambda i, j, k: (k, i))
    r_spec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
    w_spec = pl.BlockSpec((bm, 1), lambda i, j, k: (i, 0))
    out_shape = jax.ShapeDtypeStruct((Bp, Jp), acc_r.dtype)

    outr, outi = pl.pallas_call(
        _fold_kernel,
        grid=grid,
        in_specs=[a_spec, a_spec, b_spec, b_spec, r_spec, r_spec, w_spec],
        out_specs=[a_spec, a_spec],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(ar_p, ai_p, bc_p, bs_p, rr_p, ri_p, w_p)
    return outr[:B, :J], outi[:B, :J]


def _colpass_kernel(ar_ref, ai_ref, xr_ref, xi_ref, br_ref, bi_ref,
                    or_ref, oi_ref, *, reduce_f):
    """One fused column-pass output tile: out (+)= A_f @ X_sf @ B_f.

    The grid iterates (s, i, j, f, k) with f/k innermost, so the output
    tile stays resident in VMEM across the whole facet × contraction
    sweep — the prepare matmul (dot #1) and the operator contraction
    (dot #2) never round-trip a partial through HBM, which is what the
    separate XLA einsum dispatches in `_colpass_einsum_body` cost us.
    With ``reduce_f`` the facet axis folds into the accumulator
    (forward body: P_s = Σ_f A0_f @ Xn_sf @ B1_f); without it each
    facet writes its own output plane (backward body: Z_sf)."""
    f = pl.program_id(3)
    k = pl.program_id(4)
    first = (f == 0) & (k == 0) if reduce_f else k == 0

    @pl.when(first)
    def _init():
        or_ref[...] = jnp.zeros_like(or_ref)
        oi_ref[...] = jnp.zeros_like(oi_ref)

    ar = ar_ref[0]     # [bm, P]
    ai = ai_ref[0]
    xr = xr_ref[0, 0]  # [P, bk]
    xi = xi_ref[0, 0]
    br = br_ref[0]     # [bk, bn]
    bi = bi_ref[0]
    # HIGHEST matches the einsum body's matmul_precision default
    dot = functools.partial(
        jnp.dot,
        preferred_element_type=or_ref.dtype,
        precision=jax.lax.Precision.HIGHEST,
    )
    tr = dot(ar, xr) - dot(ai, xi)  # [bm, bk]
    ti = dot(ar, xi) + dot(ai, xr)
    pr = dot(tr, br) - dot(ti, bi)  # [bm, bn]
    pi = dot(tr, bi) + dot(ti, br)
    or_ref[...] += pr.reshape(or_ref.shape)
    oi_ref[...] += pi.reshape(oi_ref.shape)


@functools.partial(
    jax.jit, static_argnames=("reduce_f", "bm", "bn", "bk", "interpret")
)
def colpass_pallas(ar, ai, xr, xi, br, bi, *, reduce_f=True, bm=256,
                   bn=256, bk=256, interpret=False):
    """Fused complex triple product A_f @ X_sf @ B_f over an S block.

    The column pass's whole per-subgrid contraction — prepare matmul,
    operator einsums, complex recombination — as ONE grid program:

    * forward body: A = A0 [F, xM, m], X = gathered facet columns
      [S, F, m, m], B = B1 [F, m, xM], ``reduce_f=True`` →
      out [S, xM, xM] (facet sum folded into the VMEM accumulator).
      Dot #1 IS the prepare matmul, so the [F, xM, yN] H transient of
      the einsum body never exists.
    * backward body: A = E0 [F, m, xM], X = embedded subgrids
      [S, 1, xM, xM] (broadcast over f), B = E1 [F, xM, m],
      ``reduce_f=False`` → out [S, F, m, m].

    :param ar, ai: [F, M, P] left operator planes
    :param xr, xi: [S, Fx, P, Q] per-subgrid middle planes; Fx is F or
        1 (broadcast over the facet axis)
    :param br, bi: [F, Q, N] right operator planes
    :param reduce_f: sum over the facet axis into the accumulator
    :param bm, bn, bk: tile sizes (M rows, N cols, Q contraction); the
        P contraction runs whole per grid step (padded to 128 lanes)
    :param interpret: run in the Pallas interpreter (any backend)
    """
    F, M, P = ar.shape
    S, Fx = xr.shape[0], xr.shape[1]
    Q, N = br.shape[1], br.shape[2]
    bm, bn, bk = min(bm, M), min(bn, N), min(bk, Q)

    ar_p = _pad_to(_pad_to(ar, bm, 1), 128, 2)
    ai_p = _pad_to(_pad_to(ai, bm, 1), 128, 2)
    xr_p = _pad_to(_pad_to(xr, 128, 2), bk, 3)
    xi_p = _pad_to(_pad_to(xi, 128, 2), bk, 3)
    br_p = _pad_to(_pad_to(br, bk, 1), bn, 2)
    bi_p = _pad_to(_pad_to(bi, bk, 1), bn, 2)
    Mp, Pp = ar_p.shape[1], ar_p.shape[2]
    Qp, Np = br_p.shape[1], br_p.shape[2]

    grid = (S, Mp // bm, Np // bn, F, Qp // bk)
    a_spec = pl.BlockSpec((1, bm, Pp), lambda s, i, j, f, k: (f, i, 0))
    if Fx == 1:
        x_spec = pl.BlockSpec(
            (1, 1, Pp, bk), lambda s, i, j, f, k: (s, 0, 0, k))
    else:
        x_spec = pl.BlockSpec(
            (1, 1, Pp, bk), lambda s, i, j, f, k: (s, f, 0, k))
    b_spec = pl.BlockSpec((1, bk, bn), lambda s, i, j, f, k: (f, k, j))
    if reduce_f:
        o_spec = pl.BlockSpec((1, bm, bn), lambda s, i, j, f, k: (s, i, j))
        out_shape = jax.ShapeDtypeStruct((S, Mp, Np), ar.dtype)
    else:
        o_spec = pl.BlockSpec(
            (1, 1, bm, bn), lambda s, i, j, f, k: (s, f, i, j))
        out_shape = jax.ShapeDtypeStruct((S, F, Mp, Np), ar.dtype)

    outr, outi = pl.pallas_call(
        functools.partial(_colpass_kernel, reduce_f=reduce_f),
        grid=grid,
        in_specs=[a_spec, a_spec, x_spec, x_spec, b_spec, b_spec],
        out_specs=[o_spec, o_spec],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(ar_p, ai_p, xr_p, xi_p, br_p, bi_p)
    return outr[..., :M, :N], outi[..., :M, :N]
