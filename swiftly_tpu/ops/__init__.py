"""Numerical ops: L0 primitives, PSWF windows, and the SwiftlyCore."""

from .core import SwiftlyCore, validate_core_params
from .io_slices import (
    create_slice,
    roll_and_extract_mid,
    roll_and_extract_mid_axis,
)
from .oracle import (
    generate_masks,
    make_facet_from_sources,
    make_real_facet_plane_from_sources,
    make_subgrid_from_sources,
    mask_from_slices,
)
from .pswf import pswf_fb, pswf_fn, pswf_samples

__all__ = [
    "SwiftlyCore",
    "validate_core_params",
    "create_slice",
    "roll_and_extract_mid",
    "roll_and_extract_mid_axis",
    "generate_masks",
    "make_facet_from_sources",
    "make_real_facet_plane_from_sources",
    "make_subgrid_from_sources",
    "mask_from_slices",
    "pswf_fb",
    "pswf_fn",
    "pswf_samples",
]
