"""Numpy twins of the L0 primitives.

Same signatures and semantics as :mod:`swiftly_tpu.ops.primitives`, executed
eagerly with numpy. This is the host/reference backend: it runs anywhere,
keeps full float64 precision, and serves as the behavioural cross-check for
the JAX backend (the reference repo plays the same game between its numpy
core and the native `ska_sdp_func` core, see
/root/reference/src/ska_sdp_exec_swiftly/fourier_transform/core.py:487).
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "broadcast_along",
    "extract_mid",
    "fft",
    "ifft",
    "pad_mid",
    "roll_axis",
    "wrapped_extract",
    "wrapped_embed",
]


def ndim(a) -> int:
    """Logical dimensionality of `a`."""
    return a.ndim


def broadcast_along(vec, ndim: int, axis: int):
    """Reshape a 1D vector so it broadcasts along `axis` of an `ndim` array."""
    shape = [1] * ndim
    shape[axis] = -1
    return np.reshape(vec, shape)


def pad_mid(a, n: int, axis: int):
    """Zero-pad `a` to size `n` along `axis`, keeping the centre aligned."""
    n0 = a.shape[axis]
    if n == n0:
        return a
    before = n // 2 - n0 // 2
    pads = [(0, 0)] * a.ndim
    pads[axis] = (before, n - n0 - before)
    return np.pad(a, pads)


def extract_mid(a, n: int, axis: int):
    """Extract the centred length-`n` window along `axis`."""
    n0 = a.shape[axis]
    if n == n0:
        return a
    start = n0 // 2 - n // 2
    sl = [slice(None)] * a.ndim
    sl[axis] = slice(start, start + n)
    return a[tuple(sl)]


def fft(a, axis: int):
    """Centred-zero FFT along one axis."""
    return np.fft.fftshift(
        np.fft.fft(np.fft.ifftshift(a, axes=axis), axis=axis), axes=axis
    )


def ifft(a, axis: int):
    """Centred-zero inverse FFT along one axis."""
    return np.fft.fftshift(
        np.fft.ifft(np.fft.ifftshift(a, axes=axis), axis=axis), axes=axis
    )


def roll_axis(a, shift, axis: int):
    """np.roll along one axis."""
    return np.roll(a, int(shift), axis=axis)


def wrapped_extract(a, n: int, shift, axis: int):
    """Gather the length-`n` centre window of `a` after a circular shift."""
    size = a.shape[axis]
    idx = (size // 2 - n // 2 + np.arange(n) + int(shift)) % size
    return np.take(a, idx, axis=axis)


def wrapped_embed(a, n: int, shift, axis: int):
    """Scatter `a` into the centre of a length-`n` zero array, then shift."""
    m = a.shape[axis]
    idx = (n // 2 - m // 2 + np.arange(m) + int(shift)) % n
    moved = np.moveaxis(a, axis, 0)
    out = np.zeros((n,) + moved.shape[1:], dtype=a.dtype)
    out[idx] = moved
    return np.moveaxis(out, 0, axis)
