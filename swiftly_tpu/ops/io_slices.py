"""Host-side wrap-around slicing for big-array IO (no rolls materialised).

When facets/subgrids are read out of (or written into) a full-size image or
grid array held on disk or host memory, rolling the full N² array to centre a
chunk would defeat the whole point of the streaming transform. Instead the
wrapped window [centre+offset-w/2, centre+offset+w/2) is decomposed into at
most two contiguous intervals modulo the array size, which are then copied
slice-by-slice.

API parity with the reference L0 layer (/root/reference/src/
ska_sdp_exec_swiftly/fourier_transform/fourier_algorithm.py:10-51,141-216):
``create_slice``, ``roll_and_extract_mid``, ``roll_and_extract_mid_axis``.
Implemented independently via a generic modular interval split.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "create_slice",
    "roll_and_extract_mid",
    "roll_and_extract_mid_axis",
]


def create_slice(fill, axis_val, dims: int, axis: int) -> tuple:
    """n-dim index tuple: `axis_val` at `axis`, `fill` everywhere else.

    Parity: reference ``create_slice`` (``fourier_algorithm.py:10-35``).
    """
    if not isinstance(dims, int) or not isinstance(axis, int):
        raise ValueError("create_slice: dims and axis must be integers")
    return tuple(axis_val if d == axis else fill for d in range(dims))


def roll_and_extract_mid(size: int, offset: int, window: int) -> list:
    """Slices covering the centred window of a rolled axis, without rolling.

    Returns 1 or 2 slices of a length-`size` axis that, concatenated, equal
    ``extract_mid(roll(x, -offset), window)``. The window
    ``[size//2 + offset - window//2, ... + window)`` is split into contiguous
    intervals modulo `size`.

    Parity: reference ``roll_and_extract_mid``
    (``fourier_algorithm.py:141-175``).
    """
    if window > size:
        raise ValueError(f"Window {window} larger than axis size {size}")
    start = size // 2 + offset - window // 2
    end = start + window
    # Reduce so that start lies in [0, size)
    shift = (start % size) - start
    start += shift
    end += shift
    if end <= size:
        return [slice(start, end)]
    return [slice(start, size), slice(0, end - size)]


def roll_and_extract_mid_axis(data, offset: int, window: int, axis: int):
    """Gather the wrapped centred window along `axis` of a host array.

    Equivalent to ``extract_mid(np.roll(data, -offset, axis), window, axis)``
    but copies only the window. Parity: reference
    ``roll_and_extract_mid_axis`` (``fourier_algorithm.py:178-215``).
    """
    slices = roll_and_extract_mid(data.shape[axis], offset, window)
    out_shape = list(data.shape)
    out_shape[axis] = window
    out = np.empty(out_shape, dtype=data.dtype)
    pos = 0
    for sl in slices:
        n = sl.stop - sl.start
        dst = create_slice(slice(None), slice(pos, pos + n), data.ndim, axis)
        src = create_slice(slice(None), sl, data.ndim, axis)
        out[dst] = data[src]
        pos += n
    return out
