"""Prolate-spheroidal wave function (PSWF) window precomputation.

Host-side (scipy) — the PSWF is evaluated once per configuration at facet
resolution and shipped to the device as constants:

* ``Fb`` — reciprocal of the PSWF: the convolution-correction applied to
  facets (image space).
* ``Fn`` — the PSWF subsampled at grid resolution: the window applied to
  facet contributions (grid space).

Parity: reference ``SwiftlyCore._calculate_pswf/_Fb/_Fn``
(/root/reference/src/ska_sdp_exec_swiftly/fourier_transform/core.py:104-150).
See VLA Scientific Memoranda 129, 131, 132 for the PSWF background.
"""

from __future__ import annotations

import numpy as np
import scipy.special

from .primitives import coordinates

__all__ = ["pswf_samples", "pswf_fb", "pswf_fn"]

# scipy.special.pro_ang1 can crash when asked to fill very large arrays in
# one call; evaluating in bounded chunks is reliable at every size we use.
_CHUNK = 500


def pswf_samples(W: float, yN_size: int) -> np.ndarray:
    """Zeroth-order PSWF sampled at facet resolution.

    Evaluated on 2*coordinates(yN_size), i.e. [-1, 1). The first sample
    (at exactly -1) is defined as 0.

    :param W: grid-space support of the window (the tuning parameter)
    :param yN_size: padded facet size (number of samples)
    """
    x = 2 * coordinates(yN_size)
    out = np.empty(yN_size, dtype=float)
    c = np.pi * W / 2
    for lo in range(1, yN_size, _CHUNK):
        hi = min(lo + _CHUNK, yN_size)
        out[lo:hi] = scipy.special.pro_ang1(0, 0, c, x[lo:hi])[0]
    out[0] = 0.0
    return out


def pswf_fb(pswf: np.ndarray) -> np.ndarray:
    """Facet correction: elementwise reciprocal (skipping the zero sample)."""
    return 1.0 / pswf[1:]


def pswf_fn(pswf: np.ndarray, N: int, xM_size: int, yN_size: int) -> np.ndarray:
    """Contribution window: the PSWF subsampled with stride N/xM_size.

    Result has length xM_size*yN_size/N (the contribution size).
    """
    stride = N // xM_size
    start = (yN_size // 2) % stride
    return pswf[start::stride]
