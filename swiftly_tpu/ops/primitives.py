"""L0 array primitives — JAX/TPU implementations.

These are the building blocks of the streaming distributed Fourier transform
(facet <-> subgrid). Functional parity with the reference numpy layer
(/root/reference/src/ska_sdp_exec_swiftly/fourier_transform/fourier_algorithm.py),
re-designed for XLA:

* All *sizes* are static (compile-time); all *offsets* are dynamic (traced),
  so a single compiled program serves every facet/subgrid offset of a config.
* Centre-pad + roll and roll + centre-extract chains are fused into single
  wrapped gather/scatter helpers (`wrapped_extract` / `wrapped_embed`) so XLA
  moves only the small window instead of rolling full-size arrays.

Centre conventions (must match reference `fourier_algorithm.py:64-93` exactly):
  - the centre index of a length-n axis is n//2
  - extract_mid keeps indices [c - n//2, c - n//2 + n) of the source
  - pad_mid places the source at [n//2 - n0//2, n//2 - n0//2 + n0) of the target
Both formulas are parity-correct for even and odd n.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "broadcast_along",
    "coordinates",
    "extract_mid",
    "fft",
    "ifft",
    "pad_mid",
    "roll_axis",
    "wrapped_extract",
    "wrapped_embed",
]


def ndim(a) -> int:
    """Logical dimensionality of `a` (see planar_backend for why this
    is namespace-provided rather than ``a.ndim``)."""
    return a.ndim


def coordinates(n: int) -> np.ndarray:
    """1D coordinate array spanning [-0.5, 0.5) with 0 at index n//2.

    Host-side (numpy): used for PSWF precomputation and tests only.
    Parity: reference ``fourier_algorithm.py:125-138``.
    """
    half = n // 2
    return (np.arange(n) - half) / n


def broadcast_along(vec, ndim: int, axis: int):
    """Reshape a 1D vector so it broadcasts along `axis` of an `ndim` array.

    Parity: reference ``broadcast`` (``fourier_algorithm.py:38-50``).
    """
    shape = [1] * ndim
    shape[axis] = -1
    return jnp.reshape(vec, shape)


def pad_mid(a, n: int, axis: int):
    """Zero-pad `a` to size `n` along `axis`, keeping the centre aligned.

    Static-size operation. Parity: reference ``pad_mid``
    (``fourier_algorithm.py:53-73``).
    """
    n0 = a.shape[axis]
    if n == n0:
        return a
    before = n // 2 - n0 // 2
    pads = [(0, 0)] * a.ndim
    pads[axis] = (before, n - n0 - before)
    return jnp.pad(a, pads)


def extract_mid(a, n: int, axis: int):
    """Extract the centred length-`n` window along `axis` (inverse of pad_mid).

    Static-size operation. Parity: reference ``extract_mid``
    (``fourier_algorithm.py:76-93``).
    """
    n0 = a.shape[axis]
    if n == n0:
        return a
    start = n0 // 2 - n // 2
    return jax.lax.slice_in_dim(a, start, start + n, axis=axis)


def fft(a, axis: int):
    """Centred-zero FFT (image -> grid space) along one axis.

    fftshift(fft(ifftshift(x))). Parity: reference ``fft``
    (``fourier_algorithm.py:96-107``).
    """
    return jnp.fft.fftshift(
        jnp.fft.fft(jnp.fft.ifftshift(a, axes=axis), axis=axis), axes=axis
    )


def ifft(a, axis: int):
    """Centred-zero inverse FFT (grid -> image space) along one axis.

    Parity: reference ``ifft`` (``fourier_algorithm.py:110-122``).
    """
    return jnp.fft.fftshift(
        jnp.fft.ifft(jnp.fft.ifftshift(a, axes=axis), axis=axis), axes=axis
    )


def roll_axis(a, shift, axis: int):
    """jnp.roll along one axis with a (possibly traced) shift."""
    return jnp.roll(a, shift, axis=axis)


def wrapped_extract(a, n: int, shift, axis: int):
    """Extract the length-`n` centre window of `a` after a circular shift.

    Equivalent to ``extract_mid(roll(a, -shift, axis), n, axis)`` but moves
    only `n` elements instead of rolling the full array. `shift` may be a
    traced scalar; `n` is static. Formulated as one contiguous
    dynamic-slice of `a` extended by its own head — a sequential-DMA
    pattern TPUs execute far faster than a gather.
    """
    size = a.shape[axis]
    start = jnp.mod(size // 2 - n // 2 + shift, size)
    buf = jnp.concatenate(
        [a, jax.lax.slice_in_dim(a, 0, n, axis=axis)], axis=axis
    )
    return jax.lax.dynamic_slice_in_dim(buf, start, n, axis=axis)


def wrapped_embed(a, n: int, shift, axis: int):
    """Embed `a` into the centre of a length-`n` zero array, then shift.

    Equivalent to ``roll(pad_mid(a, n, axis), shift, axis)`` with
    wraparound, but moves only ``a.shape[axis]`` elements. `shift` may be
    traced; `n` is static (`n >= a.shape[axis]`). Adjoint of
    :func:`wrapped_extract`: one contiguous dynamic-update-slice into an
    extended zero buffer whose tail is folded back onto its head.
    """
    m = a.shape[axis]
    start = jnp.mod(n // 2 - m // 2 + shift, n)
    buf_shape = list(a.shape)
    buf_shape[axis] = n + m
    buf = jnp.zeros(buf_shape, dtype=a.dtype)
    buf = jax.lax.dynamic_update_slice_in_dim(buf, a, start, axis=axis)
    main = jax.lax.slice_in_dim(buf, 0, n, axis=axis)
    wrap = jax.lax.slice_in_dim(buf, n, n + m, axis=axis)
    sl = [slice(None)] * a.ndim
    sl[axis] = slice(0, m)
    return main.at[tuple(sl)].add(wrap)
