"""SwiftlyCore — the eight streaming-FT primitives, TPU-first.

Implements the facet->subgrid and subgrid->facet pipelines of the streaming
distributed Fourier transform:

  facet -> subgrid:  prepare_facet -> extract_from_facet -> add_to_subgrid
                     -> finish_subgrid
  subgrid -> facet:  prepare_subgrid -> extract_from_subgrid -> add_to_facet
                     -> finish_facet

Behavioural parity with the reference numpy/native cores
(/root/reference/src/ska_sdp_exec_swiftly/fourier_transform/core.py:20-929),
but formulated TPU-first:

* every pad+roll / roll+extract chain is a single wrapped gather or scatter
  of the *small* window (`wrapped_extract` / `wrapped_embed`), never a roll
  of the full padded array;
* sizes are static, offsets are traced — one XLA program per (config, shape),
  reused for every facet/subgrid offset;
* the math lives in module-level pure functions (`*_math`) parameterised by
  an array-namespace module, so the same code runs as the eager numpy
  backend and as the jitted JAX backend, and is directly `vmap`-able over
  stacked facets/subgrids for the mesh-parallel path.

All primitives are linear in their array argument; accumulation order is
therefore irrelevant and the facet-contribution sum can be computed as a
`psum` over a facet-sharded mesh axis (see swiftly_tpu.parallel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import numpy_backend as npk
from . import planar_backend as plk
from . import primitives as jxk
from .pswf import pswf_fb, pswf_fn, pswf_samples

__all__ = ["SwiftlyCore", "validate_core_params"]


def validate_core_params(N: int, xM_size: int, yN_size: int) -> None:
    """Check the divisibility constraints that make offsets exact.

    Parity: reference ``check_params`` (``core.py:55-74``).
    """
    if N % yN_size != 0:
        raise ValueError(
            f"Image size {N} must be divisible by padded facet size {yN_size}"
        )
    if N % xM_size != 0:
        raise ValueError(
            f"Image size {N} must be divisible by padded subgrid size {xM_size}"
        )
    if (xM_size * yN_size) % N != 0:
        raise ValueError(
            f"Contribution size xM_size*yN_size/N must be an integer "
            f"(got {xM_size}*{yN_size}/{N})"
        )


# ---------------------------------------------------------------------------
# The eight primitives as pure math functions.
#
# `p` is the array-namespace module (swiftly_tpu.ops.primitives for JAX,
# swiftly_tpu.ops.numpy_backend for numpy). All window vectors and sizes are
# explicit arguments, making the functions trivially jit/vmap-compatible.
# ---------------------------------------------------------------------------


def scaled_offset(off, num, N):
    """``floor((off mod N) * num / N)`` — int32-overflow-safe.

    Offsets are traced int32 inside jitted programs (jax silently keeps
    int32 without x64), and the direct product ``off * num`` overflows
    once it crosses 2**31 — at the 128k catalogue scale
    (off1 ~ 1.3e5 x yN 6.5e4 = 8.6e9) the wrapped product lands the
    extraction window 2**15 positions away from the true one (measured;
    undetectable with a single-point-source model whose far columns are
    ~1e-17 tails either way). Reducing ``off`` mod N first is exact
    because the result is only ever consumed mod ``num`` (shifts of
    period-``num`` windows), and the staged 8-bit-limb divmod bounds the
    partial products by ``(N >> 8) * num`` (the ``hi * num`` term; the
    recombination term is below ``2**8 * (N + num)``) — asserted below,
    and true with an order of magnitude to spare for the whole catalogue
    (128k: (2**17 >> 8) * 2**16 = 2**25).

    Works for python ints, numpy int64 and traced int32 alike (pure
    ``>> & // %`` arithmetic).
    """
    assert (N >> 8) * num < 1 << 31 and (N + num) << 8 < 1 << 31, (N, num)
    r = off % N
    hi, lo = r >> 8, r & 0xFF
    t = hi * num
    q1, r1 = t // N, t % N
    return (q1 << 8) + ((r1 << 8) + lo * num) // N


def prepare_facet_math(p, Fb, yN_size, facet, facet_off, axis):
    """Correct facet by Fb, embed at its offset in the padded frame, iFFT.

    Output lives in image space at padded-facet resolution (size yN along
    `axis`). Parity: reference ``prepare_facet`` (``core.py:189-222``).
    """
    n = facet.shape[axis]
    fb = p.extract_mid(Fb, n, 0)
    weighted = facet * p.broadcast_along(fb, p.ndim(facet), axis)
    embedded = p.wrapped_embed(weighted, yN_size, facet_off, axis)
    return p.ifft(embedded, axis)


def extract_from_facet_math(p, xM_yN_size, N, yN_size, prep_facet, subgrid_off, axis):
    """Down-select the compact contribution of a prepared facet to a subgrid.

    The output (size xM_yN along `axis`) is the only data that ever travels
    between a facet and a subgrid. Parity: reference ``extract_from_facet``
    (``core.py:224-253``).
    """
    scaled = scaled_offset(subgrid_off, yN_size, N)
    window = p.wrapped_extract(prep_facet, xM_yN_size, scaled, axis)
    return p.roll_axis(window, scaled, axis)


def add_to_subgrid_math(p, Fn, xM_size, N, contrib, facet_off, axis):
    """Transform one facet contribution into its padded-subgrid summand.

    FFT to grid space, window by Fn in the facet-centred frame, and embed at
    the facet offset in the padded subgrid frame. Summing the results over
    all facets (in any order — the op is linear) yields the padded subgrid.
    Parity: reference ``add_to_subgrid`` (``core.py:255-285``), with the
    accumulation (`out`/add_mode) lifted to the caller.
    """
    scaled = scaled_offset(facet_off, xM_size, N)
    spectrum = p.roll_axis(p.fft(contrib, axis), -scaled, axis)
    windowed = spectrum * p.broadcast_along(Fn, p.ndim(contrib), axis)
    return p.wrapped_embed(windowed, xM_size, scaled, axis)


def finish_subgrid_math(p, subgrid_size, summed, subgrid_offs):
    """iFFT the summed padded subgrid and cut out the true subgrid (all axes).

    Parity: reference ``finish_subgrid`` (``core.py:287-325``).
    """
    out = summed
    for axis in range(p.ndim(out)):
        out = p.wrapped_extract(
            p.ifft(out, axis), subgrid_size, subgrid_offs[axis], axis
        )
    return out


def prepare_subgrid_math(p, xM_size, subgrid, subgrid_offs):
    """Embed a subgrid at its offsets in the padded frame and FFT (all axes).

    Parity: reference ``prepare_subgrid`` (``core.py:328-368``).
    """
    out = subgrid
    for axis in range(p.ndim(out)):
        out = p.fft(p.wrapped_embed(out, xM_size, subgrid_offs[axis], axis), axis)
    return out


def extract_from_subgrid_math(p, Fn, xM_yN_size, xM_size, N, prep_subgrid, facet_off, axis):
    """Extract and window the contribution of a prepared subgrid to a facet.

    Parity: reference ``extract_from_subgrid`` (``core.py:370-406``).
    """
    scaled = scaled_offset(facet_off, xM_size, N)
    window = p.wrapped_extract(prep_subgrid, xM_yN_size, scaled, axis)
    windowed = window * p.broadcast_along(Fn, p.ndim(window), axis)
    return p.ifft(p.roll_axis(windowed, scaled, axis), axis)


def add_to_facet_math(p, yN_size, N, contrib, subgrid_off, axis):
    """Embed a subgrid contribution in the padded-facet frame for summation.

    Linear; sum over subgrids in any order. Parity: reference
    ``add_to_facet`` (``core.py:408-449``) with accumulation lifted out.
    """
    scaled = scaled_offset(subgrid_off, yN_size, N)
    centred = p.roll_axis(contrib, -scaled, axis)
    return p.wrapped_embed(centred, yN_size, scaled, axis)


def finish_facet_math(p, Fb, facet_size, summed, facet_off, axis):
    """FFT the contribution sum, cut the facet window, correct by Fb.

    Parity: reference ``finish_facet`` (``core.py:452-484``).
    """
    fb = p.extract_mid(Fb, facet_size, 0)
    window = p.wrapped_extract(p.fft(summed, axis), facet_size, facet_off, axis)
    return window * p.broadcast_along(fb, p.ndim(window), axis)


# ---------------------------------------------------------------------------
# SwiftlyCore: configuration + window constants + backend dispatch
# ---------------------------------------------------------------------------


def _apply_out(result, out=None, add=False):
    """Reference-compatible `out=` handling (functional for JAX arrays)."""
    if out is None:
        return result
    if out.shape != result.shape:
        raise ValueError(f"Output shape {out.shape}, expected {result.shape}")
    if isinstance(out, np.ndarray):
        if add:
            out += np.asarray(result)
        else:
            out[...] = np.asarray(result)
        return out
    return out + result if add else result


class SwiftlyCore:
    """Streaming distributed Fourier transform core.

    Holds the configuration (W, N, xM_size, yN_size), precomputes the PSWF
    window constants, and exposes the eight per-axis primitives for both
    directions. Four backends, one behavioural contract:

    * ``backend="jax"`` — jit-compiled XLA programs (complex dtypes);
      offsets are traced, so each primitive compiles once per array shape.
    * ``backend="planar"`` — TPU-native: complex data as (..., 2) real
      pairs, FFTs as MXU matmuls (for TPUs without complex/FFT support).
    * ``backend="numpy"`` — eager float64 host reference.
    * ``backend="native"`` — compiled C++ host kernels (swiftly_tpu.native),
      the role the ska-sdp-func C library plays for the reference.

    :param W: PSWF grid-space support parameter
    :param N: total (virtual) image size
    :param xM_size: padded subgrid size
    :param yN_size: padded facet size
    :param backend: "jax" or "numpy"
    :param dtype: complex dtype for device constants (JAX backend); defaults
        to complex128 when x64 is enabled, else complex64
    """

    def __init__(self, W, N, xM_size, yN_size, backend="jax", dtype=None):
        validate_core_params(N, xM_size, yN_size)
        self.W = W
        self.N = N
        self.xM_size = xM_size
        self.yN_size = yN_size
        self.xM_yN_size = xM_size * yN_size // N
        self.backend = backend

        pswf = pswf_samples(W, yN_size)
        fb = pswf_fb(pswf)
        fn = pswf_fn(pswf, N, xM_size, yN_size)

        if backend == "numpy":
            self._p = npk
            self._Fb = fb
            self._Fn = fn
        elif backend == "native":
            # Compiled C++ host kernels (see swiftly_tpu/native) — the
            # role the external ska-sdp-func C library plays for the
            # reference (core.py:487-929).
            from ..native import NativeKernels

            self._p = npk
            self._Fb = fb
            self._Fn = fn
            self._native = NativeKernels(N, xM_size, yN_size, fb, fn)
        elif backend == "jax":
            self._p = jxk
            if dtype is None:
                dtype = (
                    jnp.complex128
                    if jax.config.jax_enable_x64
                    else jnp.complex64
                )
            real = jnp.finfo(jnp.dtype(dtype)).dtype
            self.dtype = jnp.dtype(dtype)
            self._Fb = jnp.asarray(fb, dtype=real)
            self._Fn = jnp.asarray(fn, dtype=real)
            self._jit_cache = {}
        elif backend == "planar":
            # TPU-native path: complex data as (..., 2) real pairs, FFT via
            # MXU matmuls. The only backend that runs on TPUs without
            # complex/FFT support (which includes this environment's).
            self._p = plk
            if dtype is None:
                dtype = (
                    jnp.float64
                    if jax.config.jax_enable_x64
                    else jnp.float32
                )
            self.dtype = jnp.dtype(dtype)
            self._Fb = jnp.asarray(fb, dtype=self.dtype)
            self._Fn = jnp.asarray(fn, dtype=self.dtype)
            self._jit_cache = {}
        else:
            raise ValueError(f"Unknown SwiFTly backend: {backend}")

    # -- layout properties -------------------------------------------------

    @property
    def subgrid_off_step(self):
        """All subgrid offsets must be multiples of this (= N/yN_size)."""
        return self.N // self.yN_size

    @property
    def facet_off_step(self):
        """All facet offsets must be multiples of this (= N/xM_size)."""
        return self.N // self.xM_size

    def __repr__(self):
        return (
            f"{type(self).__name__}(W={self.W}, N={self.N}, "
            f"xM_size={self.xM_size}, yN_size={self.yN_size}, "
            f"backend={self.backend!r})"
        )

    def _key(self):
        return (
            type(self),
            self.W,
            self.N,
            self.xM_size,
            self.yN_size,
            self.backend,
            str(getattr(self, "dtype", None)),
        )

    # Hash/eq by defining parameters: cores are static arguments to the
    # jitted batch kernels, and equal parameters imply identical window
    # constants, so compiled programs are shared across equal cores.
    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, SwiftlyCore) and self._key() == other._key()

    # -- backend dispatch --------------------------------------------------

    def _run(self, name, fn, *args, static=()):
        """Run `fn(p, *bound, *args)`; jitted & cached for the JAX backend."""
        if self.backend == "numpy":
            return fn(*args)
        if self.backend == "native":
            raise AssertionError(
                "native backend must dispatch before _run"
            )  # pragma: no cover
        key = (name, static)
        jitted = self._jit_cache.get(key)
        if jitted is None:
            jitted = jax.jit(fn)
            self._jit_cache[key] = jitted
        return jitted(*args)

    def _prep(self, a):
        if self.backend in ("numpy", "native"):
            return np.asarray(a, dtype=complex)
        if self.backend == "planar":
            if not np.iscomplexobj(a) and a.shape and a.shape[-1] == 2:
                return jnp.asarray(a, dtype=self.dtype)  # already planar
            return plk.to_planar(a, dtype=self.dtype)
        return jnp.asarray(a, dtype=self.dtype)

    def to_planar(self, a):
        """Convert complex input to this core's planar representation."""
        return plk.to_planar(a, dtype=self.dtype)

    @staticmethod
    def from_planar(a):
        """Convert a planar (..., 2) result back to numpy complex."""
        return plk.from_planar(a)

    def as_complex(self, a) -> np.ndarray:
        """Return any backend's result as a numpy complex array."""
        if self.backend == "planar":
            return plk.from_planar(a)
        return np.asarray(a)

    # -- facet -> subgrid --------------------------------------------------

    def prepare_facet(self, facet, facet_off, axis, out=None):
        """Prepare a facet for contribution extraction (per axis).

        Expensive (full-size iFFT); intended to be done once per facet and
        reused for every subgrid.
        """
        if self.backend == "native":
            return _apply_out(
                self._native.prepare_facet(facet, facet_off, axis), out
            )
        fn = functools.partial(
            prepare_facet_math, self._p, self._Fb, self.yN_size, axis=axis
        )
        return _apply_out(self._run("pf", fn, self._prep(facet), facet_off, static=(axis,)), out)

    def extract_from_facet(self, prep_facet, subgrid_off, axis, out=None):
        """Extract a facet's compact contribution to one subgrid (per axis)."""
        if self.backend == "native":
            return _apply_out(
                self._native.extract_from_facet(prep_facet, subgrid_off, axis),
                out,
            )
        fn = functools.partial(
            extract_from_facet_math,
            self._p,
            self.xM_yN_size,
            self.N,
            self.yN_size,
            axis=axis,
        )
        return _apply_out(self._run("ef", fn, self._prep(prep_facet), subgrid_off, static=(axis,)), out)

    def add_to_subgrid(self, facet_contrib, facet_off, axis, out=None):
        """Turn a facet contribution into its padded-subgrid summand.

        Returns the summand; with ``out`` given, adds into/onto it
        (reference add-semantics, ``core.py:285``).
        """
        if self.backend == "native":
            # Native kernels accumulate into `out` in place themselves.
            return self._native.add_to_subgrid(
                facet_contrib, facet_off, axis, out=out
            )
        fn = functools.partial(
            add_to_subgrid_math, self._p, self._Fn, self.xM_size, self.N, axis=axis
        )
        return _apply_out(
            self._run("as", fn, self._prep(facet_contrib), facet_off, static=(axis,)),
            out,
            add=True,
        )

    def finish_subgrid(self, summed_contribs, subgrid_off, subgrid_size, out=None):
        """Finish a subgrid from summed contributions (all axes at once)."""
        data = self._prep(summed_contribs)
        offs = self._as_offsets(subgrid_off, self._p.ndim(data))
        if self.backend == "native":
            return _apply_out(
                self._native.finish_subgrid(data, offs, subgrid_size), out
            )
        fn = functools.partial(finish_subgrid_math, self._p, subgrid_size)
        return _apply_out(
            self._run("fs", fn, data, offs, static=(subgrid_size,)),
            out,
        )

    # -- subgrid -> facet --------------------------------------------------

    def prepare_subgrid(self, subgrid, subgrid_off, out=None):
        """Embed + FFT a subgrid into image space (all axes at once)."""
        data = self._prep(subgrid)
        offs = self._as_offsets(subgrid_off, self._p.ndim(data))
        if self.backend == "native":
            return _apply_out(self._native.prepare_subgrid(data, offs), out)
        fn = functools.partial(prepare_subgrid_math, self._p, self.xM_size)
        return _apply_out(self._run("ps", fn, data, offs), out)

    def extract_from_subgrid(self, prep_subgrid, facet_off, axis, out=None):
        """Extract a subgrid's windowed contribution to one facet (per axis)."""
        if self.backend == "native":
            return _apply_out(
                self._native.extract_from_subgrid(
                    prep_subgrid, facet_off, axis
                ),
                out,
            )
        fn = functools.partial(
            extract_from_subgrid_math,
            self._p,
            self._Fn,
            self.xM_yN_size,
            self.xM_size,
            self.N,
            axis=axis,
        )
        return _apply_out(self._run("es", fn, self._prep(prep_subgrid), facet_off, static=(axis,)), out)

    def add_to_facet(self, subgrid_contrib, subgrid_off, axis, out=None):
        """Turn a subgrid contribution into its padded-facet summand.

        Returns the summand; with ``out`` given, adds into/onto it.
        """
        if self.backend == "native":
            return self._native.add_to_facet(
                subgrid_contrib, subgrid_off, axis, out=out
            )
        fn = functools.partial(
            add_to_facet_math, self._p, self.yN_size, self.N, axis=axis
        )
        return _apply_out(
            self._run("af", fn, self._prep(subgrid_contrib), subgrid_off, static=(axis,)),
            out,
            add=True,
        )

    def finish_facet(self, summed, facet_off, facet_size, axis, out=None):
        """Finish a facet from summed subgrid contributions (per axis)."""
        if self.backend == "native":
            return _apply_out(
                self._native.finish_facet(summed, facet_off, facet_size, axis),
                out,
            )
        fn = functools.partial(
            finish_facet_math, self._p, self._Fb, facet_size, axis=axis
        )
        return _apply_out(
            self._run("ff", fn, self._prep(summed), facet_off, static=(facet_size, axis)),
            out,
        )

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _as_offsets(off, ndim):
        """Normalise scalar/list offsets to a per-axis list."""
        if isinstance(off, (list, tuple)):
            if len(off) != ndim:
                raise ValueError("One offset required per array dimension")
            return list(off)
        if ndim != 1:
            raise ValueError("One offset required per array dimension")
        return [off]
