"""Native C++ kernel backend — build, bindings, and the NativeKernels class.

Role parity with the reference's `ska-sdp-func` native library and its
`SwiftlyCoreFunc` wrapper (/root/reference/src/ska_sdp_exec_swiftly/
fourier_transform/core.py:487-929): a compiled host backend behind the same
eight-primitive API, complex128, with accumulate semantics and
pickling-by-parameters (the native handle is rebuilt on unpickle, as the
reference does for Dask scatter — here for multi-process host pipelines).

The shared library is compiled from `swiftly_native.cpp` on first use with
g++ (-O3 -fopenmp) and cached next to the source keyed by a source hash, so
a fresh checkout builds once and subsequent imports load instantly.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path

import numpy as np

__all__ = ["NativeKernels", "load_library", "native_available"]

_SRC = Path(__file__).with_name("swiftly_native.cpp")
_LIB = None
_LIB_ERR = None


def _build_library() -> Path:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _SRC.with_name(f"_swiftly_native_{tag}.so")
    if out.exists():
        return out
    # Compile into a temp file then atomically rename, so concurrent
    # importers never load a half-written library.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(_SRC.parent))
    os.close(fd)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
        str(_SRC), "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    except subprocess.CalledProcessError as err:
        raise RuntimeError(
            f"Native backend build failed:\n{err.stderr}"
        ) from err
    finally:
        if os.path.exists(tmp):  # compile failed or g++ missing
            os.unlink(tmp)
    return out


def load_library():
    """Build (if needed) and load the native library; cached per process."""
    global _LIB, _LIB_ERR
    if _LIB is not None:
        return _LIB
    if _LIB_ERR is not None:
        raise _LIB_ERR
    try:
        lib = ctypes.CDLL(str(_build_library()))
    except (RuntimeError, OSError) as err:  # toolchain missing etc.
        _LIB_ERR = RuntimeError(f"Native backend unavailable: {err}")
        raise _LIB_ERR from err

    i64 = ctypes.c_int64
    dptr = ctypes.POINTER(ctypes.c_double)
    lib.sw_create.restype = ctypes.c_void_p
    lib.sw_create.argtypes = [i64, i64, i64, dptr, dptr]
    lib.sw_destroy.argtypes = [ctypes.c_void_p]
    per_axis = [ctypes.c_void_p, dptr, dptr, i64, i64, i64]
    lib.sw_prepare_facet.argtypes = per_axis + [i64]
    lib.sw_extract_from_facet.argtypes = per_axis
    lib.sw_add_to_subgrid.argtypes = per_axis
    lib.sw_extract_from_subgrid.argtypes = per_axis
    lib.sw_add_to_facet.argtypes = per_axis
    lib.sw_finish_subgrid_axis.argtypes = per_axis + [i64]
    lib.sw_prepare_subgrid_axis.argtypes = per_axis + [i64]
    lib.sw_finish_facet_axis.argtypes = per_axis + [i64]
    lib.sw_add_to_subgrid_2d.argtypes = [
        ctypes.c_void_p, dptr, dptr, i64, i64,
    ]
    lib.sw_num_threads.restype = ctypes.c_int
    _LIB = lib
    return lib


def native_available() -> bool:
    """True if the native library can be built/loaded on this host."""
    try:
        load_library()
        return True
    except RuntimeError:
        return False


def _cbuf(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


class NativeKernels:
    """Handle to a native Swiftly kernel set for one configuration.

    Methods mirror the math-function layer (ops/core.py): per-axis
    primitives take (array, offset, axis); `add_*` accumulate into `out`.
    Arrays are contiguous numpy complex128; 1D and 2D supported.
    """

    def __init__(self, N: int, xM_size: int, yN_size: int,
                 fb: np.ndarray, fn: np.ndarray):
        self._params = (N, xM_size, yN_size)
        self._fb = np.ascontiguousarray(fb, dtype=float)
        self._fn = np.ascontiguousarray(fn, dtype=float)
        # sw_create copies yN-1 / xM*yN/N doubles unconditionally — length
        # mismatches must be caught here, not read out of bounds there.
        if self._fb.shape != (yN_size - 1,):
            raise ValueError(
                f"Fb must have {yN_size - 1} samples, got {self._fb.shape}"
            )
        m = xM_size * yN_size // N if N else 0
        if self._fn.shape != (m,):
            raise ValueError(
                f"Fn must have {m} samples, got {self._fn.shape}"
            )
        self._lib = load_library()
        self._handle = self._lib.sw_create(
            N, xM_size, yN_size, _cbuf(self._fb), _cbuf(self._fn)
        )
        if not self._handle:
            raise ValueError(
                f"Invalid native Swiftly parameters N={N}, "
                f"xM={xM_size}, yN={yN_size}"
            )
        self.N, self.xM_size, self.yN_size = N, xM_size, yN_size
        self.xM_yN_size = xM_size * yN_size // N

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.sw_destroy(self._handle)
            self._handle = None

    # Rebuild the handle on unpickle (native state is not serialisable) —
    # same approach as the reference wrapper (core.py:513-525).
    def __reduce__(self):
        return (NativeKernels, self._params + (self._fb, self._fn))

    @staticmethod
    def _lanes(shape, axis):
        """Map (shape, axis) onto the [pre, n, post] lane decomposition."""
        axis = axis % len(shape)
        pre = int(np.prod(shape[:axis], dtype=int))
        post = int(np.prod(shape[axis + 1 :], dtype=int))
        return pre, post

    @staticmethod
    def _prep(a) -> np.ndarray:
        return np.ascontiguousarray(a, dtype=complex)

    def _out(self, shape, axis, n, out, zero):
        out_shape = list(shape)
        out_shape[axis % len(shape)] = n
        if out is not None:
            if list(out.shape) != out_shape:
                raise ValueError(
                    f"Output shape {out.shape}, expected {tuple(out_shape)}"
                )
            if out.dtype != np.complex128 or not out.flags.c_contiguous:
                raise ValueError("Output must be contiguous complex128")
            return out
        if zero:
            return np.zeros(out_shape, dtype=complex)
        return np.empty(out_shape, dtype=complex)

    def _axis_op(self, fn, a, axis, n_out, out=None, zero_out=False,
                 extra=()):
        a = self._prep(a)
        pre, post = self._lanes(a.shape, axis)
        res = self._out(a.shape, axis, n_out, out, zero_out)
        fn(self._handle, _cbuf(a), _cbuf(res), pre, post,
           *(int(x) for x in extra))
        return res

    # -- facet -> subgrid ---------------------------------------------------

    def _check_facet_size(self, n):
        # Fb has yN-1 samples; the kernels index Fb[(yN-1)//2 - n//2 + j]
        # for j < n, so any facet larger than yN-1 would read out of bounds.
        if n > self.yN_size - 1:
            raise ValueError(
                f"Facet size {n} exceeds Fb support {self.yN_size - 1}"
            )

    def prepare_facet(self, facet, facet_off, axis):
        facet = self._prep(facet)
        nF = facet.shape[axis]
        self._check_facet_size(nF)
        pre, post = self._lanes(facet.shape, axis)
        res = self._out(facet.shape, axis, self.yN_size, None, False)
        self._lib.sw_prepare_facet(
            self._handle, _cbuf(facet), _cbuf(res), pre, nF, post,
            int(facet_off),
        )
        return res

    def extract_from_facet(self, prep_facet, subgrid_off, axis):
        return self._axis_op(
            self._lib.sw_extract_from_facet, prep_facet, axis,
            self.xM_yN_size, extra=(subgrid_off,),
        )

    def add_to_subgrid(self, contrib, facet_off, axis, out=None):
        return self._axis_op(
            self._lib.sw_add_to_subgrid, contrib, axis, self.xM_size,
            out=out, zero_out=True, extra=(facet_off,),
        )

    def add_to_subgrid_2d(self, contrib, facet_offs, out=None):
        """Fused both-axes add_to_subgrid (single native call)."""
        contrib = self._prep(contrib)
        m = self.xM_yN_size
        if contrib.shape != (m, m):
            raise ValueError(f"Contribution must be [{m}, {m}]")
        out = self._out((self.xM_size, self.xM_size), 0, self.xM_size,
                        out, True)
        self._lib.sw_add_to_subgrid_2d(
            self._handle, _cbuf(contrib), _cbuf(out),
            int(facet_offs[0]), int(facet_offs[1]),
        )
        return out

    def finish_subgrid(self, summed, subgrid_offs, subgrid_size):
        res = self._prep(summed)
        for axis, off in enumerate(subgrid_offs):
            res = self._axis_op(
                self._lib.sw_finish_subgrid_axis, res, axis, subgrid_size,
                extra=(off, subgrid_size),
            )
        return res

    # -- subgrid -> facet ---------------------------------------------------

    def prepare_subgrid(self, subgrid, subgrid_offs):
        res = self._prep(subgrid)
        for axis, off in enumerate(subgrid_offs):
            sz = res.shape[axis]
            res = self._axis_op(
                self._lib.sw_prepare_subgrid_axis, res, axis, self.xM_size,
                extra=(off, sz),
            )
        return res

    def extract_from_subgrid(self, prep_subgrid, facet_off, axis):
        return self._axis_op(
            self._lib.sw_extract_from_subgrid, prep_subgrid, axis,
            self.xM_yN_size, extra=(facet_off,),
        )

    def add_to_facet(self, contrib, subgrid_off, axis, out=None):
        return self._axis_op(
            self._lib.sw_add_to_facet, contrib, axis, self.yN_size,
            out=out, zero_out=True, extra=(subgrid_off,),
        )

    def finish_facet(self, summed, facet_off, facet_size, axis):
        self._check_facet_size(facet_size)
        return self._axis_op(
            self._lib.sw_finish_facet_axis, summed, axis, facet_size,
            extra=(facet_off, facet_size),
        )
