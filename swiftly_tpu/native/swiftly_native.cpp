// Native (host C++) kernels for the streaming distributed Fourier transform.
//
// Role parity with the reference's external `ska-sdp-func` C library
// (consumed as ska_sdp_func.fourier_transforms.swiftly.Swiftly,
// /root/reference/src/ska_sdp_exec_swiftly/fourier_transform/core.py:487-929):
// an opaque handle holding the configuration + window constants, and the
// eight streaming-FT primitives operating on caller-provided complex128
// buffers, with accumulate (+=) semantics where the dataflow sums
// contributions. Implemented from scratch — self-contained FFT (iterative
// radix-2 for power-of-two sizes, Bluestein chirp-z for the rest), OpenMP
// lane parallelism, no external dependencies.
//
// Array model: every per-axis operation sees its operand as [pre, n, post]
// — a bundle of pre*post independent lanes of length n strided by `post`.
// The Python wrapper maps (ndim, axis) onto that decomposition, so 1D and
// 2D arrays and both axes share one code path.
//
// All offsets use floor division/modulo (Python semantics), so negative
// offsets behave identically to the numpy backend.

#include <cmath>
#include <complex>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

using cplx = std::complex<double>;
using std::int64_t;

namespace {

constexpr double PI = 3.141592653589793238462643383279502884;

int64_t floordiv(int64_t a, int64_t b) {
    int64_t q = a / b;
    if ((a % b) != 0 && ((a < 0) != (b < 0))) --q;
    return q;
}

int64_t pmod(int64_t a, int64_t n) {
    int64_t r = a % n;
    return r < 0 ? r + n : r;
}

bool is_pow2(int64_t n) { return n > 0 && (n & (n - 1)) == 0; }

int64_t next_pow2(int64_t n) {
    int64_t p = 1;
    while (p < n) p <<= 1;
    return p;
}

// ---------------------------------------------------------------------------
// FFT plans
// ---------------------------------------------------------------------------

// Radix-2 plan: bit-reversal permutation + per-stage twiddle tables.
struct Radix2Plan {
    int64_t n;
    std::vector<int64_t> rev;
    std::vector<cplx> twiddle;  // exp(-2*pi*i*k/n) for k in [0, n/2)

    explicit Radix2Plan(int64_t n_) : n(n_), rev(n_), twiddle(n_ / 2) {
        int log2n = 0;
        while ((int64_t(1) << log2n) < n) ++log2n;
        for (int64_t i = 0; i < n; ++i) {
            int64_t r = 0;
            for (int b = 0; b < log2n; ++b)
                if (i & (int64_t(1) << b)) r |= int64_t(1) << (log2n - 1 - b);
            rev[i] = r;
        }
        for (int64_t k = 0; k < n / 2; ++k)
            twiddle[k] = std::polar(1.0, -2.0 * PI * double(k) / double(n));
    }

    // In-place DFT of contiguous data; sign=-1 forward, +1 inverse
    // (unnormalised — caller divides by n for the inverse).
    void run(cplx* a, int sign) const {
        for (int64_t i = 0; i < n; ++i) {
            int64_t j = rev[i];
            if (i < j) std::swap(a[i], a[j]);
        }
        for (int64_t len = 2; len <= n; len <<= 1) {
            int64_t half = len >> 1, step = n / len;
            for (int64_t base = 0; base < n; base += len) {
                for (int64_t k = 0; k < half; ++k) {
                    cplx w = twiddle[k * step];
                    if (sign > 0) w = std::conj(w);
                    cplx u = a[base + k];
                    cplx v = a[base + k + half] * w;
                    a[base + k] = u + v;
                    a[base + k + half] = u - v;
                }
            }
        }
    }
};

// Bluestein chirp-z plan for arbitrary n: linear convolution with the
// chirp via a power-of-two cyclic FFT of size M >= 2n-1.
struct BluesteinPlan {
    int64_t n, M;
    Radix2Plan fftM;
    std::vector<cplx> chirp;      // u[j] = exp(-i*pi*j^2/n)  (forward sign)
    std::vector<cplx> kernel_fft; // FFT of the wrapped conjugate chirp

    explicit BluesteinPlan(int64_t n_)
        : n(n_), M(next_pow2(2 * n_ - 1)), fftM(M), chirp(n_) {
        for (int64_t j = 0; j < n; ++j) {
            // j^2 mod 2n keeps the phase argument small and exact
            int64_t m = (j * j) % (2 * n);
            chirp[j] = std::polar(1.0, -PI * double(m) / double(n));
        }
        std::vector<cplx> b(M, cplx(0, 0));
        for (int64_t j = 0; j < n; ++j) {
            cplx c = std::conj(chirp[j]);
            b[j] = c;
            if (j > 0) b[M - j] = c;
        }
        fftM.run(b.data(), -1);
        kernel_fft = std::move(b);
    }

    // Transform contiguous data of length n using caller scratch (size M).
    void run(cplx* a, int sign, cplx* scratch) const {
        for (int64_t j = 0; j < n; ++j) {
            cplx u = sign < 0 ? chirp[j] : std::conj(chirp[j]);
            scratch[j] = a[j] * u;
        }
        std::memset(reinterpret_cast<void*>(scratch + n), 0,
                    sizeof(cplx) * size_t(M - n));
        fftM.run(scratch, -1);
        if (sign < 0) {
            for (int64_t j = 0; j < M; ++j) scratch[j] *= kernel_fft[j];
        } else {
            for (int64_t j = 0; j < M; ++j)
                scratch[j] *= std::conj(kernel_fft[j]);
        }
        fftM.run(scratch, +1);
        double inv = 1.0 / double(M);  // unnormalised inverse above
        for (int64_t k = 0; k < n; ++k) {
            cplx u = sign < 0 ? chirp[k] : std::conj(chirp[k]);
            a[k] = scratch[k] * u * inv;
        }
    }
};

struct FftPlan {
    int64_t n;
    std::unique_ptr<Radix2Plan> r2;
    std::unique_ptr<BluesteinPlan> blu;

    explicit FftPlan(int64_t n_) : n(n_) {
        if (is_pow2(n))
            r2 = std::make_unique<Radix2Plan>(n);
        else
            blu = std::make_unique<BluesteinPlan>(n);
    }

    int64_t scratch_size() const { return blu ? blu->M : 0; }

    void run(cplx* a, int sign, cplx* scratch) const {
        if (r2)
            r2->run(a, sign);
        else
            blu->run(a, sign, scratch);
    }
};

// ---------------------------------------------------------------------------
// Handle
// ---------------------------------------------------------------------------

struct Swiftly {
    int64_t N, xM, yN, m;              // m = contribution size xM*yN/N
    std::vector<double> Fb;            // size yN-1 (reciprocal PSWF)
    std::vector<double> Fn;            // size m (subsampled PSWF)
    std::map<int64_t, std::unique_ptr<FftPlan>> plans;
    std::mutex plan_mutex;

    const FftPlan& plan(int64_t n) {
        std::lock_guard<std::mutex> lock(plan_mutex);
        auto it = plans.find(n);
        if (it == plans.end())
            it = plans.emplace(n, std::make_unique<FftPlan>(n)).first;
        return *it->second;
    }
};

// Per-lane worker: gathers a strided lane into contiguous scratch, applies
// a centred (fftshift) FFT, and scatters results back with wrap-around.
struct Lane {
    std::vector<cplx> buf, fft_scratch;

    void ensure(int64_t n, int64_t scratch) {
        if (int64_t(buf.size()) < n) buf.resize(n);
        if (int64_t(fft_scratch.size()) < scratch) fft_scratch.resize(scratch);
    }

    // Centred transform of buf[0:n]: fftshift(fft(ifftshift(x))). The
    // shifts are index rotations folded into a rotate-copy.
    void centred_fft(const FftPlan& p, int64_t n, int sign) {
        ensure(2 * n, p.scratch_size());
        cplx* tmp = buf.data() + n;
        int64_t h = n / 2;
        for (int64_t j = 0; j < n; ++j) tmp[j] = buf[(j + h) % n];
        p.run(tmp, sign, fft_scratch.data());
        if (sign > 0) {
            double inv = 1.0 / double(n);
            for (int64_t j = 0; j < n; ++j) tmp[j] *= inv;
        }
        for (int64_t j = 0; j < n; ++j) buf[(j + h) % n] = tmp[j];
    }
};

// Iterate lanes of [pre, n, post] in parallel; `fn(lane, in_lane, out_lane)`.
template <typename F>
void for_lanes(int64_t pre, int64_t post, const cplx* in, cplx* out,
               int64_t n_in, int64_t n_out, F&& fn) {
#if defined(_OPENMP)
#pragma omp parallel
    {
        Lane lane;
#pragma omp for collapse(2) schedule(static)
        for (int64_t i = 0; i < pre; ++i)
            for (int64_t k = 0; k < post; ++k)
                fn(lane, in + (i * n_in) * post + k,
                   out + (i * n_out) * post + k);
    }
#else
    Lane lane;
    for (int64_t i = 0; i < pre; ++i)
        for (int64_t k = 0; k < post; ++k)
            fn(lane, in + (i * n_in) * post + k,
               out + (i * n_out) * post + k);
#endif
}

}  // namespace

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

extern "C" {

void* sw_create(int64_t N, int64_t xM, int64_t yN, const double* fb,
                const double* fn) {
    if (N <= 0 || xM <= 0 || yN <= 0 || N % xM || N % yN ||
        (xM * yN) % N)
        return nullptr;
    auto* h = new Swiftly;
    h->N = N;
    h->xM = xM;
    h->yN = yN;
    h->m = xM * yN / N;
    h->Fb.assign(fb, fb + (yN - 1));
    h->Fn.assign(fn, fn + h->m);
    return h;
}

void sw_destroy(void* handle) { delete static_cast<Swiftly*>(handle); }

// facet[nF] * Fb window, embedded at facet_off in the yN frame, centred iFFT.
// In: [pre, nF, post] -> out: [pre, yN, post].
void sw_prepare_facet(void* handle, const double* in, double* out,
                      int64_t pre, int64_t nF, int64_t post,
                      int64_t facet_off) {
    auto* h = static_cast<Swiftly*>(handle);
    const int64_t yN = h->yN;
    const int64_t fb0 = (yN - 1) / 2 - nF / 2;  // extract_mid of Fb
    const int64_t emb0 = yN / 2 - nF / 2 + facet_off;
    const auto& plan = h->plan(yN);
    for_lanes(pre, post, reinterpret_cast<const cplx*>(in),
              reinterpret_cast<cplx*>(out), nF, yN,
              [&](Lane& lane, const cplx* src, cplx* dst) {
                  lane.ensure(2 * yN, plan.scratch_size());
                  std::fill(lane.buf.begin(), lane.buf.begin() + yN,
                            cplx(0, 0));
                  for (int64_t j = 0; j < nF; ++j)
                      lane.buf[pmod(emb0 + j, yN)] =
                          src[j * post] * h->Fb[fb0 + j];
                  lane.centred_fft(plan, yN, +1);
                  for (int64_t j = 0; j < yN; ++j) dst[j * post] = lane.buf[j];
              });
}

// Gather the m-sized contribution window of a prepared facet for one
// subgrid offset. In: [pre, yN, post] -> out: [pre, m, post].
void sw_extract_from_facet(void* handle, const double* in, double* out,
                           int64_t pre, int64_t post, int64_t subgrid_off) {
    auto* h = static_cast<Swiftly*>(handle);
    const int64_t yN = h->yN, m = h->m;
    const int64_t scaled = floordiv(subgrid_off * yN, h->N);
    const int64_t src0 = yN / 2 - m / 2 + scaled;
    for_lanes(pre, post, reinterpret_cast<const cplx*>(in),
              reinterpret_cast<cplx*>(out), yN, m,
              [&](Lane&, const cplx* src, cplx* dst) {
                  for (int64_t j = 0; j < m; ++j)
                      dst[pmod(j + scaled, m) * post] =
                          src[pmod(src0 + j, yN) * post];
              });
}

// Contribution -> padded-subgrid summand: centred FFT, roll by -scaled,
// Fn window, embed at +scaled; ACCUMULATES into out.
// In: [pre, m, post] -> out (+=): [pre, xM, post].
void sw_add_to_subgrid(void* handle, const double* in, double* out,
                       int64_t pre, int64_t post, int64_t facet_off) {
    auto* h = static_cast<Swiftly*>(handle);
    const int64_t xM = h->xM, m = h->m;
    const int64_t scaled = floordiv(facet_off * xM, h->N);
    const int64_t emb0 = xM / 2 - m / 2 + scaled;
    const auto& plan = h->plan(m);
    for_lanes(pre, post, reinterpret_cast<const cplx*>(in),
              reinterpret_cast<cplx*>(out), m, xM,
              [&](Lane& lane, const cplx* src, cplx* dst) {
                  lane.ensure(2 * m, plan.scratch_size());
                  for (int64_t j = 0; j < m; ++j) lane.buf[j] = src[j * post];
                  lane.centred_fft(plan, m, -1);
                  for (int64_t j = 0; j < m; ++j)
                      dst[pmod(emb0 + j, xM) * post] +=
                          lane.buf[pmod(j + scaled, m)] * h->Fn[j];
              });
}

// One axis of finish_subgrid: centred iFFT then wrapped extract of the
// true subgrid window. In: [pre, xM, post] -> out: [pre, sg_size, post].
void sw_finish_subgrid_axis(void* handle, const double* in, double* out,
                            int64_t pre, int64_t post, int64_t subgrid_off,
                            int64_t sg_size) {
    auto* h = static_cast<Swiftly*>(handle);
    const int64_t xM = h->xM;
    const int64_t src0 = xM / 2 - sg_size / 2 + subgrid_off;
    const auto& plan = h->plan(xM);
    for_lanes(pre, post, reinterpret_cast<const cplx*>(in),
              reinterpret_cast<cplx*>(out), xM, sg_size,
              [&](Lane& lane, const cplx* src, cplx* dst) {
                  lane.ensure(2 * xM, plan.scratch_size());
                  for (int64_t j = 0; j < xM; ++j) lane.buf[j] = src[j * post];
                  lane.centred_fft(plan, xM, +1);
                  for (int64_t j = 0; j < sg_size; ++j)
                      dst[j * post] = lane.buf[pmod(src0 + j, xM)];
              });
}

// One axis of prepare_subgrid: wrapped embed at the subgrid offset, then
// centred FFT. In: [pre, sg_size, post] -> out: [pre, xM, post].
void sw_prepare_subgrid_axis(void* handle, const double* in, double* out,
                             int64_t pre, int64_t post, int64_t subgrid_off,
                             int64_t sg_size) {
    auto* h = static_cast<Swiftly*>(handle);
    const int64_t xM = h->xM;
    const int64_t emb0 = xM / 2 - sg_size / 2 + subgrid_off;
    const auto& plan = h->plan(xM);
    for_lanes(pre, post, reinterpret_cast<const cplx*>(in),
              reinterpret_cast<cplx*>(out), sg_size, xM,
              [&](Lane& lane, const cplx* src, cplx* dst) {
                  lane.ensure(2 * xM, plan.scratch_size());
                  std::fill(lane.buf.begin(), lane.buf.begin() + xM,
                            cplx(0, 0));
                  for (int64_t j = 0; j < sg_size; ++j)
                      lane.buf[pmod(emb0 + j, xM)] = src[j * post];
                  lane.centred_fft(plan, xM, -1);
                  for (int64_t j = 0; j < xM; ++j) dst[j * post] = lane.buf[j];
              });
}

// Windowed contribution of a prepared subgrid to one facet: gather the m
// window at scaled offset, Fn multiply, roll back, centred iFFT.
// In: [pre, xM, post] -> out: [pre, m, post].
void sw_extract_from_subgrid(void* handle, const double* in, double* out,
                             int64_t pre, int64_t post, int64_t facet_off) {
    auto* h = static_cast<Swiftly*>(handle);
    const int64_t xM = h->xM, m = h->m;
    const int64_t scaled = floordiv(facet_off * xM, h->N);
    const int64_t src0 = xM / 2 - m / 2 + scaled;
    const auto& plan = h->plan(m);
    for_lanes(pre, post, reinterpret_cast<const cplx*>(in),
              reinterpret_cast<cplx*>(out), xM, m,
              [&](Lane& lane, const cplx* src, cplx* dst) {
                  lane.ensure(2 * m, plan.scratch_size());
                  for (int64_t j = 0; j < m; ++j)
                      lane.buf[pmod(j + scaled, m)] =
                          src[pmod(src0 + j, xM) * post] * h->Fn[j];
                  lane.centred_fft(plan, m, +1);
                  for (int64_t j = 0; j < m; ++j) dst[j * post] = lane.buf[j];
              });
}

// Subgrid contribution -> padded-facet summand: roll to centre, embed at
// the scaled subgrid offset; ACCUMULATES into out.
// In: [pre, m, post] -> out (+=): [pre, yN, post].
void sw_add_to_facet(void* handle, const double* in, double* out,
                     int64_t pre, int64_t post, int64_t subgrid_off) {
    auto* h = static_cast<Swiftly*>(handle);
    const int64_t yN = h->yN, m = h->m;
    const int64_t scaled = floordiv(subgrid_off * yN, h->N);
    const int64_t emb0 = yN / 2 - m / 2 + scaled;
    for_lanes(pre, post, reinterpret_cast<const cplx*>(in),
              reinterpret_cast<cplx*>(out), m, yN,
              [&](Lane&, const cplx* src, cplx* dst) {
                  for (int64_t j = 0; j < m; ++j)
                      dst[pmod(emb0 + j, yN) * post] +=
                          src[pmod(j + scaled, m) * post];
              });
}

// One axis of finish_facet: centred FFT, wrapped extract of the facet
// window, Fb correction. In: [pre, yN, post] -> out: [pre, f_size, post].
void sw_finish_facet_axis(void* handle, const double* in, double* out,
                          int64_t pre, int64_t post, int64_t facet_off,
                          int64_t f_size) {
    auto* h = static_cast<Swiftly*>(handle);
    const int64_t yN = h->yN;
    const int64_t fb0 = (yN - 1) / 2 - f_size / 2;
    const int64_t src0 = yN / 2 - f_size / 2 + facet_off;
    const auto& plan = h->plan(yN);
    for_lanes(pre, post, reinterpret_cast<const cplx*>(in),
              reinterpret_cast<cplx*>(out), yN, f_size,
              [&](Lane& lane, const cplx* src, cplx* dst) {
                  lane.ensure(2 * yN, plan.scratch_size());
                  for (int64_t j = 0; j < yN; ++j) lane.buf[j] = src[j * post];
                  lane.centred_fft(plan, yN, -1);
                  for (int64_t j = 0; j < f_size; ++j)
                      dst[j * post] = lane.buf[pmod(src0 + j, yN)] *
                                      h->Fb[fb0 + j];
              });
}

// Fused 2D fast path (parity: reference add_to_subgrid_2d, core.py:752-795):
// both axes of the contribution -> padded-subgrid transform in one call,
// no intermediate crossing the language boundary.
// In: [m, m] -> out (+=): [xM, xM].
void sw_add_to_subgrid_2d(void* handle, const double* in, double* out,
                          int64_t facet_off0, int64_t facet_off1) {
    auto* h = static_cast<Swiftly*>(handle);
    const int64_t xM = h->xM, m = h->m;
    std::vector<cplx> mid(size_t(xM) * m);
    sw_add_to_subgrid(handle, in, reinterpret_cast<double*>(mid.data()),
                      /*pre=*/1, /*post=*/m, facet_off0);
    sw_add_to_subgrid(handle, reinterpret_cast<const double*>(mid.data()),
                      out, /*pre=*/xM, /*post=*/1, facet_off1);
}

int sw_num_threads() {
#if defined(_OPENMP)
    return omp_get_max_threads();
#else
    return 1;
#endif
}

}  // extern "C"
