import time, numpy as np, jax, jax.numpy as jnp
def log(*a): print(*a, file=open("/tmp/probe/log.txt","a"), flush=True)
log("=== stage micro-probe 32k")
from swiftly_tpu import SwiftlyConfig, SWIFT_CONFIGS
from swiftly_tpu.parallel.streamed import _facet_pass_fwd_j
params = dict(SWIFT_CONFIGS["32k[1]-n16k-512"]); params.setdefault("fov", 1.0)
config = SwiftlyConfig(backend="planar", dtype=jnp.float32, **params)
core = config.core
log("config ready")
F, yB, Cb, K = 9, 11264, 512, 74
block = jnp.zeros((F, yB, Cb, 2), dtype=jnp.float32)
foffs0 = jnp.asarray(np.arange(F) * 11264 % 32768)
col_offs0 = jnp.asarray(np.arange(K) * 448)
fwd = _facet_pass_fwd_j(core)
t0=time.time()
lowered = fwd.lower(block, foffs0, col_offs0)
log("lower", round(time.time()-t0,1))
t0=time.time()
compiled = lowered.compile()
log("compile", round(time.time()-t0,1))
try:
    log("mem analysis:", compiled.memory_analysis())
except Exception as e:
    log("mem analysis failed", e)
t0=time.time()
out = compiled(block, foffs0, col_offs0); jax.block_until_ready(out)
log("run1", round(time.time()-t0,1), out.shape)
t0=time.time()
out = compiled(block, foffs0, col_offs0); jax.block_until_ready(out)
log("run2", round(time.time()-t0,1))
t0=time.time()
h = np.asarray(out)
log("download", round(time.time()-t0,1), h.nbytes/1e6, "MB")
