import time, numpy as np, jax, jax.numpy as jnp
def log(*a): print(*a, file=open("/tmp/probe/log.txt","a"), flush=True)
log("=== download probe")
x = jnp.ones((64*1024*1024,), jnp.float32); jax.block_until_ready(x)  # 256MB flat
t0=time.time(); h=np.asarray(x); log("flat 256MB", round(time.time()-t0,2), "->", round(h.nbytes/1e6/(time.time()-t0),1), "MB/s")
y = jnp.ones((16*1024*1024,), jnp.float32); jax.block_until_ready(y)  # 64MB
t0=time.time(); h=np.asarray(y); log("flat 64MB", round(time.time()-t0,2), "->", round(h.nbytes/1e6/(time.time()-t0),1), "MB/s")
z = jnp.ones((8, 1024, 1024, 8, 2), jnp.float32); jax.block_until_ready(z)  # 512MB 5D
t0=time.time(); h=np.asarray(z); log("5d 512MB", round(time.time()-t0,2), "->", round(h.nbytes/1e6/(time.time()-t0),1), "MB/s")
t0=time.time(); h=jax.device_get(x); log("device_get flat 256MB", round(time.time()-t0,2))
# chunked pulls of the flat array
t0=time.time()
parts=[np.asarray(x[i*8*1024*1024:(i+1)*8*1024*1024]) for i in range(8)]
log("chunked 8x32MB", round(time.time()-t0,2))
