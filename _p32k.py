import os, time, numpy as np, jax, jax.numpy as jnp
CFG = os.environ.get("CFG", "32k[1]-n16k-512")
def log(*a): print(*a, file=open("/tmp/probe/log.txt","a"), flush=True)
log("=== device-streamed fwd", CFG)
from swiftly_tpu import (SwiftlyConfig, SWIFT_CONFIGS, check_subgrid,
                         make_full_facet_cover, make_full_subgrid_cover, make_facet)
from swiftly_tpu.parallel import StreamedForward
params = dict(SWIFT_CONFIGS[CFG]); params.setdefault("fov", 1.0)
config = SwiftlyConfig(backend="planar", dtype=jnp.float32, **params)
fcs = make_full_facet_cover(config); sgs = make_full_subgrid_cover(config)
sources = [(1.0, 1, 0)]
t0=time.time()
f0 = make_facet(config.image_size, fcs[0], sources)
facet_tasks = [(fc, f0) for fc in fcs]
log("facet built+replicated", round(time.time()-t0,1))
def run(label):
    fwd = StreamedForward(config, facet_tasks, residency="device")
    t0=time.time()
    acc = None; last = None; n = 0; kept = {}
    for items, out in fwd.stream_columns(sgs, device_arrays=True):
        s = jnp.sum(out * out)  # force materialisation, keep on device
        acc = s if acc is None else acc + s
        last = out; n += len(items)
        for srow, (i, sgc) in enumerate(items):
            if i % 997 == 0: kept[i] = (sgc, out[srow])
    jax.block_until_ready(acc); jax.block_until_ready(last)
    el = time.time()-t0
    log(label, round(el,1), "n_sg", n, "G_auto", fwd._auto_col_group(len({s.off0 for s in sgs})))
    return fwd, kept, float(acc[...,0] if acc.ndim else acc)
fwd, kept, _ = run("COLD full forward (compile+upload+run)")
_, kept, _ = run("WARM full forward")
t0=time.time()
rms = max(check_subgrid(config.image_size, sgc, config.core.as_complex(np.asarray(d)), sources)
          for sgc, d in kept.values())
log("rms over", len(kept), "samples:", f"{rms:.3e}", "(pull took", round(time.time()-t0,1), "s)")
