import os, time, numpy as np, jax, jax.numpy as jnp
def log(*a): print(*a, file=open("/tmp/probe/phase.txt","a"), flush=True)
log("=== phase timing 32k")
from swiftly_tpu import SwiftlyConfig, SWIFT_CONFIGS, make_full_facet_cover, make_full_subgrid_cover
from swiftly_tpu.parallel.streamed import (_facet_pass_sampled_j, _column_pass_fwd_j,
                                            sampled_row_indices)
params = dict(SWIFT_CONFIGS["32k[1]-n16k-512"]); params.setdefault("fov", 1.0)
config = SwiftlyConfig(backend="planar", dtype=jnp.float32, **params)
core = config.core
fcs = make_full_facet_cover(config); sgs = make_full_subgrid_cover(config)
F, yB, m = 9, fcs[0].size, core.xM_yN_size
col_offs0 = sorted({sg.off0 for sg in sgs}); S = sum(1 for sg in sgs if sg.off0==col_offs0[0])
G = 4
Fr = jnp.zeros((F, yB, yB), jnp.float32); Fi = jnp.zeros((F, yB, yB), jnp.float32)
jax.block_until_ready(Fr)
e0 = jnp.asarray((np.array([fc.off0 for fc in fcs]) - yB//2).astype(np.int32))
krows = jnp.asarray(sampled_row_indices(core, col_offs0[:G]))
samfn = _facet_pass_sampled_j(core)
t0=time.time(); buf = samfn(Fr, Fi, e0, krows); jax.block_until_ready(buf)
log("samfn cold(G=4)", round(time.time()-t0,1))
for trial in range(2):
    t0=time.time(); buf = samfn(Fr, Fi, e0, krows); jax.block_until_ready(buf)
    log("samfn warm", round(time.time()-t0,2))
colfn = _column_pass_fwd_j(core, sgs[0].size)
NMBF = jax.lax.slice_in_dim(buf, 0, m, axis=1)
foffs0 = jnp.asarray([fc.off0 for fc in fcs]); foffs1 = jnp.asarray([fc.off1 for fc in fcs])
sg_offs = jnp.asarray([(col_offs0[0], s.off1) for s in sgs[:S]])
m0 = jnp.ones((S, sgs[0].size), jnp.float32); m1 = jnp.ones((S, sgs[0].size), jnp.float32)
t0=time.time(); out = colfn(NMBF, foffs0, foffs1, sg_offs, m0, m1); jax.block_until_ready(out)
log("colfn cold", round(time.time()-t0,1))
for trial in range(2):
    t0=time.time(); out = colfn(NMBF, foffs0, foffs1, sg_offs, m0, m1); jax.block_until_ready(out)
    log("colfn warm", round(time.time()-t0,2))
log("implied total: samfn*19 + colfn*74")
